"""graftcheck core: one parsed view of the tree, shared by every checker.

The tree is walked ONCE (same shape as check_metrics_coverage.py): every
package .py file is read and ast-parsed into a PyFile, and the checkers run
over that shared RepoIndex — no checker re-reads the filesystem. Findings
carry a line number for humans and a line-independent identity key
(``rule:path:scope:detail``) for the baseline, so unrelated edits above a
baselined finding cannot churn the baseline file.

Suppression contract (docs/static-analysis.md):

    x = blocking_thing()  # graftcheck: disable=GC001 — <why this is safe>

applies to findings on its own line; a standalone suppression comment
applies to the next line. The reason (anything after the dash) is
MANDATORY, and a suppression that matches no finding is itself reported —
the same rot policy the metrics guard applies to its allowlist.

Baseline contract: ``baseline.json`` next to this module holds
``{"key": <finding key>, "reason": <why fixing is not local>}`` entries for
proven-benign pre-existing findings. Every entry needs a non-empty reason,
and an entry matching no current finding is rot (fails the guard), so the
baseline can only shrink unless a justified entry is added consciously.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Callable, Iterable, Optional

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

# meta-rules (suppression/baseline hygiene). Not suppressible themselves.
META_RULES = ("GC-SUPPRESS-REASON", "GC-SUPPRESS-UNUSED", "GC-BASELINE")

# default scan surface: the package plus the asyncio/JAX-driving entrypoints
# (bench + chaos/profile scripts + the benchmark load generator). tests/ are
# deliberately out of scope — fixture files MUST violate rules.
DEFAULT_ROOTS = ("production_stack_tpu", "scripts", "benchmarks", "bench.py")

_SUPPRESS_RE = re.compile(
    r"#\s*graftcheck:\s*disable=((?:GC\d{3})(?:\s*,\s*GC\d{3})*)"
    r"(?:\s*[—–-]+\s*(\S.*))?"
)


@dataclasses.dataclass
class Finding:
    rule: str          # "GC001".."GC005" or a META_RULES id
    path: str          # repo-relative posix path
    line: int          # 1-based, for humans
    scope: str         # dotted enclosing scope ("Class.method" / "<module>")
    detail: str        # stable short identity ("time.sleep", "open via _x")
    message: str       # full human-readable description

    @property
    def key(self) -> str:
        """Line-independent identity used by baseline.json."""
        return f"{self.rule}:{self.path}:{self.scope}:{self.detail}"

    def render(self) -> str:
        return f"{self.path}:{self.line} [{self.rule}] {self.scope}: {self.message}"


@dataclasses.dataclass
class Suppression:
    line: int           # line the comment sits on
    rules: tuple        # ("GC001", ...)
    reason: str         # "" = missing (a violation)
    standalone: bool    # comment-only line -> applies to the NEXT line
    used: bool = False


class PyFile:
    def __init__(self, path: pathlib.Path, repo: pathlib.Path):
        self.abspath = path
        self.path = path.relative_to(repo).as_posix()
        self.text = path.read_text(encoding="utf-8", errors="replace")
        self.lines = self.text.splitlines()
        try:
            self.tree: Optional[ast.Module] = ast.parse(self.text)
        except SyntaxError:
            self.tree = None
        self.suppressions = self._parse_suppressions()

    def _parse_suppressions(self) -> list[Suppression]:
        out = []
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(","))
            out.append(Suppression(
                line=i,
                rules=rules,
                reason=(m.group(2) or "").strip(),
                standalone=line.strip().startswith("#"),
            ))
        return out

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        """Inline suppression on the finding's line, or a standalone comment
        directly above it."""
        for s in self.suppressions:
            if rule not in s.rules:
                continue
            if (s.line == line and not s.standalone) or (
                s.standalone and s.line == line - 1
            ):
                return s
        return None


class RepoIndex:
    """Every package .py file, read + parsed once. ``by_module`` maps dotted
    module names (``production_stack_tpu.router.app``) to PyFile so GC001 can
    resolve one level of intra-package calls through imports."""

    def __init__(self, repo: pathlib.Path = REPO,
                 roots: Iterable[str] = DEFAULT_ROOTS):
        self.repo = repo
        self.files: list[PyFile] = []
        self.by_module: dict[str, PyFile] = {}
        for root in roots:
            base = repo / root
            if base.is_file():
                self._add(base)
                continue
            for path in sorted(base.rglob("*.py")):
                # the analyzer's own sources carry example violations and
                # suppression syntax in documentation — scanning itself
                # would report its own docs as rot
                if "graftcheck" in path.parts:
                    continue
                self._add(path)

    def _add(self, path: pathlib.Path) -> None:
        pf = PyFile(path, self.repo)
        self.files.append(pf)
        mod = pf.path[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        self.by_module[mod] = pf

    def get(self, relpath: str) -> Optional[PyFile]:
        for f in self.files:
            if f.path == relpath:
                return f
        return None


# -- shared AST helpers --------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def expr_text(node: ast.AST) -> str:
    """Canonical source-ish text for expression identity (use-after-donate
    tracking compares these)."""
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 - identity only needs stability
        return ast.dump(node)


def walk_scoped(tree: ast.AST):
    """Yield (scope, node) for every function/class body node, where scope is
    the dotted enclosing def/class path ('' at module level)."""
    def visit(node, scope):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
                yield sub, child
                yield from visit(child, sub)
            else:
                yield from visit(child, scope)
    yield from visit(tree, "")


def iter_nodes_skipping_nested_defs(body: Iterable[ast.stmt]):
    """Walk statements' subtrees without descending into nested function or
    class definitions (a nested def is a different execution context — for
    GC001 it is almost always an executor thunk)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue  # a nested def is a different execution context
        stack.extend(ast.iter_child_nodes(node))


# -- incremental (--changed) support -------------------------------------------

# contract checkers diff whole surfaces against each other; a one-file diff
# filter would hide the far side of a drift, so their findings always
# survive --changed filtering (they are cheap — pure extraction + set diff)
CONTRACT_RULES = ("GC005", "GC009", "GC010")


def changed_paths(repo: pathlib.Path = REPO) -> "Optional[set[str]]":
    """Repo-relative posix paths touched in the working tree + index
    (staged, unstaged, untracked), from ``git status --porcelain``. Returns
    None when git (or the repository index) is unavailable — callers fall
    back to the full tree."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", str(repo), "status", "--porcelain",
             "--untracked-files=all"],
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    paths: set[str] = set()
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:  # rename: old -> new; the NEW path is the live one
            path = path.split(" -> ", 1)[1]
        paths.add(path.strip().strip('"'))
    return paths


def filter_changed(violations: "list[Finding]",
                   changed: "set[str]") -> "list[Finding]":
    """Pre-commit view: keep findings on changed files, every contract-rule
    finding (the drift may sit on the unchanged side), and baseline-rot
    findings only when baseline.json itself changed."""
    out = []
    for f in violations:
        if f.rule in CONTRACT_RULES:
            out.append(f)
        elif f.rule == "GC-BASELINE":
            if f.path in changed:
                out.append(f)
        elif f.path in changed:
            out.append(f)
    return out


# -- runner --------------------------------------------------------------------

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: pathlib.Path = BASELINE_PATH) -> list[dict]:
    if not path.exists():
        return []
    return json.loads(path.read_text())


def _checkers() -> list:
    from . import gc001_eventloop, gc002_donation, gc003_tracer, gc004_locks
    from . import gc005_endpoints, gc006_tasks, gc007_ownership
    from . import gc008_offloop, gc009_wire, gc010_metrics

    return [gc001_eventloop, gc002_donation, gc003_tracer, gc004_locks,
            gc005_endpoints, gc006_tasks, gc007_ownership, gc008_offloop,
            gc009_wire, gc010_metrics]


def run_graftcheck(
    repo: pathlib.Path = REPO,
    roots: Iterable[str] = DEFAULT_ROOTS,
    baseline: Optional[list[dict]] = None,
    checkers: Optional[list] = None,
    index: Optional[RepoIndex] = None,
) -> "tuple[list[Finding], dict]":
    """Run every checker over one shared RepoIndex. Returns
    ``(violations, stats)`` where violations is everything NOT silenced by a
    reasoned suppression or a matching baseline entry — including the
    hygiene meta-findings (reasonless suppression, unused suppression,
    baseline rot). Empty list == the guard passes.

    With an explicit ``checkers`` subset, hygiene checks scope to the
    selected rules: baseline entries and suppressions for UNSELECTED rules
    are neither applied nor reported as rot — `--rule GC001` on a clean
    tree must pass, not trip over another rule's silencers."""
    index = index or RepoIndex(repo, roots)
    baseline = load_baseline() if baseline is None else baseline
    active = checkers if checkers is not None else _checkers()
    active_rules = {c.RULE for c in active}
    baseline = [
        e for e in baseline
        if (e.get("key") or "").split(":", 1)[0] in active_rules
    ]
    raw: list[Finding] = []
    for checker in active:
        raw.extend(checker.check(index))

    violations: list[Finding] = []
    suppressed = 0
    for f in raw:
        pf = index.get(f.path)
        sup = pf.suppression_for(f.rule, f.line) if pf else None
        if sup is not None:
            sup.used = True
            if not sup.reason:
                violations.append(Finding(
                    "GC-SUPPRESS-REASON", f.path, sup.line, f.scope, f.detail,
                    f"suppression of {f.rule} has no reason — "
                    "'# graftcheck: disable=GCnnn — <reason>' is the contract",
                ))
            else:
                suppressed += 1
            continue
        violations.append(f)

    # baseline: reasoned entries silence matching findings; rot fails
    by_key: dict[str, list[Finding]] = {}
    for f in list(violations):
        if f.rule not in META_RULES:  # hygiene findings cannot be baselined
            by_key.setdefault(f.key, []).append(f)
    baselined = 0
    for entry in baseline:
        key = entry.get("key", "")
        reason = (entry.get("reason") or "").strip()
        matched = by_key.pop(key, None)
        if not reason:
            violations.append(Finding(
                "GC-BASELINE", "scripts/graftcheck/baseline.json", 0,
                "<baseline>", key,
                f"baseline entry {key!r} has no reason — justifications are "
                "mandatory",
            ))
            continue
        if matched is None:
            violations.append(Finding(
                "GC-BASELINE", "scripts/graftcheck/baseline.json", 0,
                "<baseline>", key,
                f"baseline entry {key!r} matches no current finding "
                "(stale — delete it)",
            ))
            continue
        for f in matched:
            violations.remove(f)
            baselined += 1

    # unused suppressions are rot, exactly like a stale baseline entry
    # (only for rules that actually ran — a GC004 suppression is not rot
    # just because this invocation only ran GC001)
    for pf in index.files:
        for s in pf.suppressions:
            if not s.used and set(s.rules) & active_rules:
                violations.append(Finding(
                    "GC-SUPPRESS-UNUSED", pf.path, s.line, "<module>",
                    f"unused:{s.line}",
                    f"suppression of {', '.join(s.rules)} matches no finding "
                    "(stale — delete it)",
                ))

    stats = {
        "files": len(index.files),
        "raw_findings": len(raw),
        "suppressed": suppressed,
        "baselined": baselined,
        "violations": len(violations),
    }
    return violations, stats
