"""GC004 — lock discipline for annotated shared state.

The engine is a two-writer system (device thread + event loop), the
collector/flight-recorder rings take writes from both, and the offload tiers
take a third (transfer threads). Attributes that NEED a lock declare it at
their initializing assignment:

    self._outputs = {}  # guarded-by: _lock

From then on, every access to that attribute IN THE SAME FILE must sit
lexically inside ``with self._lock:`` (or ``with <lock>:`` for module-level
state guarded by a module-level lock). Exempt:

- the declaring assignment itself and the rest of ``__init__`` (or module
  top level for globals) — no second thread exists yet;
- lines carrying a reasoned ``# graftcheck: disable=GC004`` suppression
  (the documented-racy patterns: benign unlocked reads of atomically
  rebound references, racy-by-design rate-limit pre-checks).

The checker is deliberately lexical (no inter-procedural lock tracking):
the repo's locking idiom is short ``with`` blocks, and a helper that
assumes its caller holds the lock should say so with a suppression — that
is documentation the next reader needs anyway.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .core import Finding, RepoIndex, expr_text

RULE = "GC004"

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")


def _annotations(pf) -> "list[tuple[str, Optional[str], str, int]]":
    """(attr, class_name or None for module globals, lock_name, line) for
    every '# guarded-by: <lock>' annotation sitting on an assignment."""
    out = []
    if pf.tree is None:
        return out
    ann_lines: dict[int, str] = {}
    for i, line in enumerate(pf.lines, start=1):
        m = _GUARD_RE.search(line)
        if m:
            ann_lines[i] = m.group(1)
    if not ann_lines:
        return out

    def scan(body, cls: Optional[str]):
        for node in body:
            if isinstance(node, ast.ClassDef):
                scan(node.body, node.name)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(node.body, cls)
                continue
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                lock = ann_lines.get(node.lineno)
                if lock is None:
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self":
                        out.append((t.attr, cls, lock, node.lineno))
                    elif isinstance(t, ast.Name) and cls is None:
                        out.append((t.id, None, lock, node.lineno))
            # descend into EVERY compound statement (loops, try/except/
            # finally, with, if): an annotated assignment on a recovery or
            # loop path must register, or the checker is a silent no-op for
            # that attribute
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(node, field, None)
                if isinstance(sub, list) and sub and isinstance(
                        sub[0], ast.stmt):
                    scan(sub, cls)
            for handler in getattr(node, "handlers", []) or []:
                scan(handler.body, cls)

    scan(pf.tree.body, None)
    return out


def _lock_exprs(lock: str, is_attr: bool) -> set[str]:
    """Source texts that count as holding `lock` in a with-statement."""
    if is_attr:
        return {f"self.{lock}", lock}
    return {lock, f"self.{lock}"}


class _AccessVisitor(ast.NodeVisitor):
    """Walk one top-level def tracking the lexical with-lock stack."""

    def __init__(self, pf, scope: str, guarded: dict, cls: Optional[str],
                 findings: list):
        self.pf = pf
        self.scope = scope
        self.guarded = guarded      # attr -> lock texts
        self.cls = cls
        self.findings = findings
        self.held: list[set] = []
        self._reported: set = set()

    def _currently_held(self) -> set:
        out: set = set()
        for h in self.held:
            out |= h
        return out

    def visit_With(self, node: ast.With):
        acquired: set = set()
        for item in node.items:
            acquired.add(expr_text(item.context_expr))
        # visit the context expressions OUTSIDE the lock scope (evaluating
        # `self._lock` itself is not an access to guarded state)
        for item in node.items:
            self.visit(item.context_expr)
        self.held.append(acquired)
        for stmt in node.body:
            self.visit(stmt)
        self.held.pop()

    # `async with lock:` holds the lock exactly like `with lock:` — the
    # asyncio-lock case is the event-loop code this suite polices
    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node):
        # nested defs run later, without this frame's locks — they are
        # visited separately by check() with their own (empty) lock stack
        return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Attribute(self, node: ast.Attribute):
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and node.attr in self.guarded):
            self._check(node, node.attr)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        if node.id in self.guarded and self.guarded[node.id].get("module"):
            self._check(node, node.id)
        self.generic_visit(node)

    def _check(self, node, attr: str) -> None:
        lock_texts = self.guarded[attr]["locks"]
        if lock_texts & self._currently_held():
            return
        # one finding per (attr, line): a read-modify-write touches the
        # attribute twice on one line but is ONE violation
        key = (attr, node.lineno)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(Finding(
            RULE, self.pf.path, node.lineno, self.scope,
            f"unlocked:{attr}",
            f"access to {attr!r} (guarded-by: "
            f"{self.guarded[attr]['lock']}) outside `with "
            f"{sorted(lock_texts)[0]}:`",
        ))


def check(index: RepoIndex) -> list[Finding]:
    findings: list[Finding] = []
    for pf in index.files:
        if pf.tree is None:
            continue
        anns = _annotations(pf)
        if not anns:
            continue
        per_class: dict[Optional[str], dict] = {}
        for attr, cls, lock, _line in anns:
            per_class.setdefault(cls, {})[attr] = {
                "lock": lock,
                "locks": _lock_exprs(lock, is_attr=cls is not None),
                "module": cls is None,
            }
        # walk every def; skip __init__ of the annotating class and module
        # top level (initialization happens before any second thread)
        for scope, node in _defs(pf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            parts = scope.split(".")
            cls = parts[-2] if len(parts) > 1 else None
            guarded = dict(per_class.get(cls, {}))
            guarded.update(per_class.get(None, {}))  # module globals apply
            if not guarded:
                continue
            if node.name == "__init__" and cls in per_class:
                # attribute state may initialize unlocked; module globals
                # accessed from __init__ still need their lock
                guarded = {k: v for k, v in guarded.items() if v["module"]}
                if not guarded:
                    continue
            v = _AccessVisitor(pf, scope, guarded, cls, findings)
            for stmt in node.body:
                v.visit(stmt)
    return findings


def _defs(tree: ast.Module):
    def visit(node, scope):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
                yield sub, child
                yield from visit(child, sub)
            else:
                yield from visit(child, scope)
    yield from visit(tree, "")
