"""Shared ownership/context analysis for GC007 and GC008.

Two pieces:

1. ``# owned-by: <context>`` annotations — the thread-ownership mirror of
   GC004's ``# guarded-by:``. An attribute (or module global) declares which
   execution context owns it at its initializing assignment:

       self._frozen: dict = {}          # owned-by: device-thread
       self._data = OrderedDict()       # owned-by: event-loop
       self._cursor = itertools.count() # owned-by: any

   Contexts: ``event-loop`` (the asyncio loop's single writer),
   ``device-thread`` (the engine step loop / executor / any worker thread),
   ``any`` (explicitly free-threaded — documentation only, never flagged).

   The registry is keyed by ATTRIBUTE NAME across the whole scan surface:
   ``self.engine._frozen`` in migration/manager.py is checked against the
   annotation in engine/engine.py — exactly the cross-file reasoning PR 10
   did by hand. Keep annotated names distinctive; if the same name is
   annotated with CONFLICTING contexts in two places, both drop out of the
   cross-file check (self-file accesses still check against the local one).

2. Execution-context inference per function, lexical and per-file:

   - ``async def`` bodies run on the event loop;
   - functions handed to ``threading.Thread(target=...)``,
     ``loop.run_in_executor(...)``, ``asyncio.to_thread(...)``,
     ``executor.submit(...)``, or the engine's ``_run_on_device_thread(...)``
     run on a worker ("device-thread") — including lambdas and nested defs
     submitted by name;
   - everything else is UNKNOWN and is never flagged (a sync helper may be
     called from either side; annotate its callers' submission sites
     instead).
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .core import PyFile

EVENT_LOOP = "event-loop"
DEVICE = "device-thread"
ANY = "any"

_OWNED_RE = re.compile(r"#\s*owned-by:\s*(event-loop|device-thread|any)\b")

# call names whose first callable argument runs on a worker thread
_SUBMIT_FIRST_ARG = {"to_thread", "submit", "_run_on_device_thread"}
# loop.run_in_executor(executor, fn, *args): fn is the SECOND argument
_SUBMIT_SECOND_ARG = {"run_in_executor"}


class Annotation:
    def __init__(self, attr: str, context: str, pf: PyFile, line: int,
                 cls: Optional[str], is_attr: bool = True):
        self.attr = attr
        self.context = context
        self.pf = pf
        self.line = line
        self.cls = cls      # declaring class, None outside any class
        self.is_attr = is_attr  # False: module-level bare-name global


def parse_annotations(pf: PyFile) -> list[Annotation]:
    """Every '# owned-by: <ctx>' annotation sitting on an assignment."""
    out: list[Annotation] = []
    if pf.tree is None:
        return out
    ann_lines: dict[int, str] = {}
    for i, line in enumerate(pf.lines, start=1):
        m = _OWNED_RE.search(line)
        if m:
            ann_lines[i] = m.group(1)
    if not ann_lines:
        return out

    def scan(body, cls: Optional[str]):
        for node in body:
            if isinstance(node, ast.ClassDef):
                scan(node.body, node.name)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(node.body, cls)
                continue
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                ctx = ann_lines.get(node.lineno)
                if ctx is not None:
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        if isinstance(t, ast.Attribute):
                            out.append(Annotation(t.attr, ctx, pf,
                                                  node.lineno, cls))
                        elif isinstance(t, ast.Name) and cls is None:
                            out.append(Annotation(t.id, ctx, pf,
                                                  node.lineno, None,
                                                  is_attr=False))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(node, field, None)
                if isinstance(sub, list) and sub and isinstance(
                        sub[0], ast.stmt):
                    scan(sub, cls)
            for handler in getattr(node, "handlers", []) or []:
                scan(handler.body, cls)

    scan(pf.tree.body, None)
    return out


def ownership_registry(
    files,
) -> "tuple[dict[str, str], dict[str, str], dict[str, tuple[dict, dict]]]":
    """(attrs, module_globals, per_file): name -> owning context.
    ``attrs`` holds attribute annotations (checked on ``x.attr`` accesses),
    ``module_globals`` holds module-level bare-name annotations (checked on
    ``Name`` accesses). Conflicting annotations (same name, different
    contexts) drop the name from the CROSS-FILE tables — that check needs
    an unambiguous claim — but each annotating file keeps its own claim in
    ``per_file[path] = (attrs, globals_)`` so self-file accesses still
    check against the local annotation instead of silently un-guarding."""
    attrs: dict[str, str] = {}
    globals_: dict[str, str] = {}
    per_file: dict[str, tuple[dict, dict]] = {}
    conflicted: set[tuple[bool, str]] = set()
    for pf in files:
        for ann in parse_annotations(pf):
            table = attrs if ann.is_attr else globals_
            is_global = table is globals_
            prev = table.get(ann.attr)
            if prev is not None and prev != ann.context:
                conflicted.add((is_global, ann.attr))
            if prev is None:
                table[ann.attr] = ann.context
            local = per_file.setdefault(pf.path, ({}, {}))
            local[1 if is_global else 0].setdefault(ann.attr, ann.context)
    for is_global, name in conflicted:
        (globals_ if is_global else attrs).pop(name, None)
    return attrs, globals_, per_file


def effective_tables(attrs: dict, globals_: dict, per_file: dict,
                     path: str) -> "tuple[dict, dict]":
    """Cross-file tables overlaid with ``path``'s own annotations, so a
    conflict elsewhere in the surface never disables checking inside the
    file that declared ownership."""
    local_attrs, local_globals = per_file.get(path, ({}, {}))
    if not local_attrs and not local_globals:
        return attrs, globals_
    return {**attrs, **local_attrs}, {**globals_, **local_globals}


# -- context inference ---------------------------------------------------------


def _callable_refs(call: ast.Call) -> list[ast.AST]:
    """Expressions submitted to run on a worker thread by ``call``."""
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    out: list[ast.AST] = []
    if name == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                out.append(kw.value)
    elif name in _SUBMIT_FIRST_ARG:
        if call.args:
            out.append(call.args[0])
    elif name in _SUBMIT_SECOND_ARG:
        if len(call.args) >= 2:
            out.append(call.args[1])
    return out


class FileContexts:
    """Parent-map-based structural view of one file: enclosing function /
    class per node, nested-def symbol tables, and the inferred execution
    context per def node."""

    _DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

    def __init__(self, pf: PyFile):
        self.pf = pf
        self.parents: dict[int, ast.AST] = {}
        self.contexts: dict[int, str] = {}
        self._methods: dict[tuple[Optional[str], str], ast.AST] = {}
        self._children: dict[Optional[int], dict[str, ast.AST]] = {None: {}}
        if pf.tree is None:
            return
        for node in ast.walk(pf.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
        for node in ast.walk(pf.tree):
            if not isinstance(node, self._DEFS):
                continue
            if isinstance(node, ast.AsyncFunctionDef):
                self.contexts[id(node)] = EVENT_LOOP
            encl_fn = self.enclosing_function(node)
            self._children.setdefault(
                id(encl_fn) if encl_fn is not None else None, {}
            )[node.name] = node
            cls = self.enclosing_class_name(node)
            if cls is not None or encl_fn is None:
                # methods and module-level defs only: a def nested in a
                # function must not shadow a same-named method/function in
                # the self./module resolution table (_children handles it)
                self._methods[(cls, node.name)] = node
        for call in [n for n in ast.walk(pf.tree)
                     if isinstance(n, ast.Call)]:
            for ref in _callable_refs(call):
                target = self._resolve_ref(ref, call)
                if target is not None:
                    # explicit submission to a worker wins over async-ness
                    self.contexts[id(target)] = DEVICE

    def _ancestors(self, node: ast.AST):
        cur = self.parents.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parents.get(id(cur))

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self._ancestors(node):
            if isinstance(anc, self._DEFS):
                return anc
        return None

    def enclosing_class_name(self, node: ast.AST) -> Optional[str]:
        for anc in self._ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc.name
            if isinstance(anc, self._DEFS):
                # a def inside a def belongs to the inner function, not
                # any outer class (a method of a class nested inside a
                # function still hits its ClassDef first, above)
                return None
        return None

    def _resolve_ref(self, ref: ast.AST, at: ast.AST) -> Optional[ast.AST]:
        if isinstance(ref, ast.Lambda):
            return ref
        if isinstance(ref, ast.Name):
            fn = self.enclosing_function(at)
            while True:
                table = self._children.get(
                    id(fn) if fn is not None else None, {}
                )
                if ref.id in table:
                    return table[ref.id]
                if fn is None:
                    return None
                fn = self.enclosing_function(fn)
        if isinstance(ref, ast.Attribute) and isinstance(ref.value, ast.Name):
            if ref.value.id == "self":
                cls = None
                for anc in self._ancestors(at):
                    if isinstance(anc, ast.ClassDef):
                        cls = anc.name
                        break
                return self._methods.get((cls, ref.attr))
        return None

    def context_of(self, def_node: ast.AST) -> Optional[str]:
        """EVENT_LOOP, DEVICE, or None (unknown — never checked).
        Lambdas submitted to an executor report DEVICE too."""
        return self.contexts.get(id(def_node))

    def iter_defs(self):
        """(dotted_scope, def_node) for every function def in the file,
        plus executor-submitted lambdas (scope suffix ``<lambda>``)."""
        if self.pf.tree is None:
            return
        for node in ast.walk(self.pf.tree):
            if isinstance(node, self._DEFS) or (
                    isinstance(node, ast.Lambda)
                    and id(node) in self.contexts):
                name = getattr(node, "name", "<lambda>")
                parts = [name]
                for anc in self._ancestors(node):
                    if isinstance(anc, (*self._DEFS, ast.ClassDef)):
                        parts.append(anc.name)
                yield ".".join(reversed(parts)), node
