"""GC006 — asyncio task lifetime.

The event loop holds only WEAK references to its tasks: a task whose result
is dropped on the floor can be garbage-collected mid-flight and simply
stops running, with no exception and no log line. PR 9 shipped this bug
TWICE in one review cycle — the cache server's directory-persistence loop
silently stopped snapshotting, and the fake engine's directory publishes
were GC'd while parked on the publisher lock (flaky chaos assertions).
Both fixes were one line: keep a strong reference.

Every ``create_task`` / ``ensure_future`` result must therefore be
RETAINED. Retention, in this repo's idioms:

- assigned to an attribute (``self._task = loop.create_task(...)``,
  ``cs._persist_task = ...``) or a subscript;
- passed as an argument to a call (``self._bg.append(create_task(...))``,
  ``tasks.add(t)``, ``asyncio.gather(create_task(...), ...)``);
- awaited or returned/yielded;
- placed in a container literal (incl. list/set comprehensions whose
  result is itself a tracked local);
- a local that is later awaited, passed as a call argument, stored, or
  used at all — EXCEPT when its only use is ``add_done_callback`` (the
  exact shipped trap: ``t.add_done_callback(tasks.discard)`` without a
  matching ``tasks.add(t)`` retains nothing).

``tg.create_task(...)`` on a TaskGroup-ish receiver (``tg``,
``task_group``, ``group``, ``nursery``) is exempt — the group owns its
tasks.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Finding, RepoIndex, dotted_name

RULE = "GC006"

_SPAWN_NAMES = ("create_task", "ensure_future")
_GROUP_RECEIVERS = {"tg", "task_group", "taskgroup", "group", "nursery"}
# receiver-method uses of a task local that do NOT keep it alive
_NON_RETAINING_METHODS = {"add_done_callback"}


def _spawn_call(node: ast.AST) -> Optional[ast.Call]:
    """The Call node when ``node`` is a create_task/ensure_future call."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    if name not in _SPAWN_NAMES:
        return None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        if fn.value.id in _GROUP_RECEIVERS:
            return None  # TaskGroup owns its tasks
    return node


def _coro_detail(call: ast.Call) -> str:
    """Stable identity for the finding key: the spawned coroutine's name."""
    if call.args:
        arg = call.args[0]
        if isinstance(arg, ast.Call):
            name = dotted_name(arg.func)
            if name:
                return name.split(".")[-1]
            if isinstance(arg.func, ast.Attribute):
                return arg.func.attr
        name = dotted_name(arg)
        if name:
            return name.split(".")[-1]
    return "task"


class _FnScanner:
    """Retention analysis for one function body (nested defs excluded —
    they are scanned as their own functions)."""

    def __init__(self, fn: ast.AST):
        self.body = fn.body
        self.parents: dict[int, ast.AST] = {}
        self.nodes: list[ast.AST] = []
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            self.nodes.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
                stack.append(child)

    def spawns(self):
        for node in self.nodes:
            call = _spawn_call(node)
            if call is not None:
                yield call

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    # -- retention of the call expression itself --------------------------

    def call_retained(self, call: ast.Call) -> "tuple[bool, Optional[str]]":
        """(retained, local_name). ``local_name`` set when the value lands
        in a bare local that needs liveness analysis."""
        node: ast.AST = call
        while True:
            parent = self.parent(node)
            if parent is None:
                return False, None
            if isinstance(parent, ast.Expr):
                return False, None  # bare statement: fire-and-forget
            if isinstance(parent, ast.Await):
                return True, None
            if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom)):
                return True, None
            if isinstance(parent, ast.Call) and node is not parent.func:
                return True, None  # argument of append/add/gather/...
            if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (parent.targets if isinstance(parent, ast.Assign)
                           else [parent.target])
                locals_only = [t for t in targets if isinstance(t, ast.Name)]
                if len(locals_only) == len(targets) and locals_only:
                    return False, locals_only[0].id  # needs liveness
                return True, None  # attribute / subscript store
            if isinstance(parent, ast.NamedExpr):
                if isinstance(parent.target, ast.Name):
                    return False, parent.target.id
                return True, None
            if isinstance(parent, (ast.List, ast.Tuple, ast.Set, ast.Dict,
                                   ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp, ast.Starred, ast.IfExp,
                                   ast.BoolOp)):
                node = parent  # the container/expr carries the task onward
                continue
            return True, None  # conservatively quiet on exotic positions

    # -- liveness of a task-holding local ---------------------------------

    def _loop_ancestors(self, node: ast.AST) -> "set[int]":
        out: set[int] = set()
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                out.add(id(cur))
            cur = self.parent(cur)
        return out

    def local_retained(self, name: str, spawn: ast.Call) -> bool:
        """A load of ``name`` retains the task only if it can execute AFTER
        the spawn: textually later, or inside a loop that also contains the
        spawn (next iteration re-reads it). A load that can only see the
        PREVIOUS task bound to the name — the respawn idiom
        ``t.cancel(); t = create_task(...)`` — retains nothing."""
        spawn_pos = (spawn.lineno, spawn.col_offset)
        spawn_loops = self._loop_ancestors(spawn)
        for node in self.nodes:
            if not isinstance(node, ast.Name) or node.id != name:
                continue
            if not isinstance(node.ctx, ast.Load):
                continue
            if ((node.lineno, node.col_offset) < spawn_pos
                    and not (spawn_loops & self._loop_ancestors(node))):
                continue  # pre-spawn load: it saw the OLD binding
            parent = self.parent(node)
            if (isinstance(parent, ast.Attribute)
                    and parent.attr in _NON_RETAINING_METHODS):
                continue  # t.add_done_callback(...) alone retains nothing
            # any OTHER load — await t, tasks.add(t), gather(*ts), return t,
            # t.cancel(), container literals — means a live reference path
            # (the Load-ctx filter above already excluded the assignment
            # target itself, which is a Store)
            return True
        return False


def _iter_functions(tree: ast.Module):
    """(scope, def_node) for every function at any depth, plus a synthetic
    module-level pseudo-function for top-level statements."""
    def visit(node, scope):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
                yield sub, child
                yield from visit(child, sub)
            elif isinstance(child, ast.ClassDef):
                sub = f"{scope}.{child.name}" if scope else child.name
                yield from visit(child, sub)
            else:
                yield from visit(child, scope)
    yield from visit(tree, "")


class _ModuleBody:
    """Adapter so module-level spawn statements get the same analysis."""

    def __init__(self, tree: ast.Module):
        self.body = tree.body


def check(index: RepoIndex) -> list[Finding]:
    findings: list[Finding] = []
    for pf in index.files:
        if pf.tree is None:
            continue
        units: list = [("<module>", _ModuleBody(pf.tree))]
        units.extend(_iter_functions(pf.tree))
        for scope, fn in units:
            scanner = _FnScanner(fn)
            for call in scanner.spawns():
                retained, local = scanner.call_retained(call)
                if retained:
                    continue
                if local is not None and scanner.local_retained(local, call):
                    continue
                coro = _coro_detail(call)
                how = (
                    f"task bound only to local {local!r} that is never "
                    "awaited, stored, or passed on"
                    if local is not None else
                    "task result discarded (bare statement)"
                )
                findings.append(Finding(
                    RULE, pf.path, call.lineno, scope or "<module>",
                    f"unretained:{coro}",
                    f"{how} — the event loop holds only a weak reference, "
                    f"so the {coro} task can be GC'd mid-flight and silently "
                    "stop (retain it in an attribute/collection, await it, "
                    "or hand it to a TaskGroup)",
                ))
    return findings
