"""GC010 — metric discipline over the hand-rolled Prometheus surface.

check_metrics_coverage guards that every metric NAME is documented and
dashboarded; nothing guards that the metrics behave like their declared
types. This repo renders exposition by hand (``# TYPE <name> counter``
literals + f-string sample lines), which makes the discipline mechanically
checkable:

- **type-conflict** — one family declared ``counter`` in one file and
  ``gauge`` in another: Prometheus keeps whichever scrape came last and
  rate() queries silently break.
- **naming** — a ``counter`` must end ``_total`` (the convention every
  dashboard query in observability/ relies on); a ``gauge`` must NOT end
  ``_total`` (it would invite rate() over a resettable value).
- **counter-decrement** — the int attribute backing a ``*_total`` family
  must never be ``-=``-mutated (counters only reset on process restart;
  a decrement makes rate() read negative and increase() lie).
- **inc-only gauge** — a ``gauge`` whose backing attribute is only ever
  ``+=``-mutated is a counter wearing the wrong type: rename it ``*_total``
  and declare it counter, or make it actually level-valued.
- **construct-once** — ``Histogram(...)`` (utils/metrics.py) built outside
  module scope / class body / ``__init__`` churns a fresh family per call
  and loses all history between scrapes.
- **label drift** — the same family rendered with different label KEY sets
  at different literal sites (``{model=...}`` here, ``{model_name=...}``
  there) splits one family into unjoinable series; a label key produced by
  interpolation (not literal text) is an open keyset the cardinality guard
  cannot audit.

Extraction is literal-anchored: only f-string sample lines whose LEADING
text is the metric name participate (dynamic-name renderers like the
shared ``Histogram.render`` are skipped — their call sites carry the
literal labels). Backing attributes resolve through two idioms: the sample
line's value expression (``f"vllm:x_total {self.n}"``) and stats-dict
literals (``{"x_total": self.n}``) rendered by a generic exposition loop.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .core import Finding, PyFile, RepoIndex

RULE = "GC010"

_TYPE_RE = re.compile(r"#\s*TYPE\s+([A-Za-z_:][A-Za-z0-9_:]*)\s+(counter|gauge|histogram)")
_NAME_RE = re.compile(r"^([A-Za-z_][A-Za-z0-9_]*:[A-Za-z0-9_:]+)")
_LABEL_KEY_RE = re.compile(r"([A-Za-z_][A-Za-z0-9_]*)=")
_PLACEHOLDER = "\x00"

_METRIC_PREFIXES = ("vllm:", "vllm_router:", "fake:")


class Sample:
    def __init__(self, name: str, labels: "Optional[frozenset]",
                 dynamic_label_key: bool, value_attr: Optional[str],
                 pf: PyFile, line: int):
        self.name = name
        self.labels = labels          # frozenset of label keys, or None
        self.dynamic_label_key = dynamic_label_key
        self.value_attr = value_attr  # self.<attr> backing the value
        self.pf = pf
        self.line = line


def _joined_text(node: ast.JoinedStr) -> str:
    """Literal text with formatted values replaced by a placeholder."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append(_PLACEHOLDER)
    return "".join(parts)


def _value_attr(node: ast.JoinedStr) -> Optional[str]:
    """self.<attr> when the LAST formatted value is a plain attribute."""
    fvs = [v for v in node.values if isinstance(v, ast.FormattedValue)]
    if not fvs:
        return None
    expr = fvs[-1].value
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


def _parse_sample(text: str) -> "Optional[tuple[str, Optional[frozenset], bool]]":
    """(name, label_keys, dynamic_label_key) for a metric-shaped line."""
    if not text.startswith(_METRIC_PREFIXES):
        return None
    m = _NAME_RE.match(text)
    if not m:
        return None
    name = m.group(1)
    rest = text[m.end():]
    if rest.startswith("{"):
        end = rest.find("}")
        if end < 0:
            return None
        block = rest[1:end]
        dynamic = False
        opaque = False
        keys = []
        for item in block.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                if _PLACEHOLDER in item:
                    # the repo idiom: a prebuilt label STRING variable
                    # interpolated as (part of) the block — keyset unknown
                    # here, audited at the site that builds the string
                    opaque = True
                continue
            key_part = item.split("=", 1)[0]
            if _PLACEHOLDER in key_part:
                dynamic = True  # a label KEY formed by interpolation
                continue
            km = _LABEL_KEY_RE.match(item)
            if km:
                keys.append(km.group(1))
        return name, (None if opaque else frozenset(keys)), dynamic
    if not rest.startswith((" ", _PLACEHOLDER)):
        return None  # prose mentioning a metric name, not a sample line
    return name, frozenset(), False


def _scan_file(pf: PyFile):
    """(type_decls, samples, stats_backings) for one file.
    stats_backings: (metric_key, attr, line) from ``{"x_total": self.x}``
    dict literals rendered by generic exposition loops."""
    types: list[tuple[str, str, int]] = []
    samples: list[Sample] = []
    stats: list[tuple[str, str, int]] = []
    if pf.tree is None:
        return types, samples, stats
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            m = _TYPE_RE.search(node.value)
            if m:
                types.append((m.group(1), m.group(2), node.lineno))
        elif isinstance(node, ast.JoinedStr):
            text = _joined_text(node)
            m = _TYPE_RE.search(text)
            if m and _PLACEHOLDER not in m.group(1):
                types.append((m.group(1), m.group(2), node.lineno))
                continue
            parsed = _parse_sample(text)
            if parsed is not None:
                name, labels, dynamic = parsed
                samples.append(Sample(
                    name, labels, dynamic, _value_attr(node), pf, node.lineno
                ))
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and k.value.endswith("_total")
                        and isinstance(v, ast.Attribute)
                        and isinstance(v.value, ast.Name)
                        and v.value.id == "self"):
                    stats.append((k.value, v.attr, v.lineno))
    return types, samples, stats


def _attr_mutations(pf: PyFile) -> "dict[str, dict]":
    """attr -> {"dec": [lines], "inc": [lines], "assign": [lines]} for
    ``self.<attr>`` mutations outside __init__/reset*."""
    out: dict[str, dict] = {}
    if pf.tree is None:
        return out

    def scan_fn(fn, exempt: bool):
        for node in ast.walk(fn):
            tgt = None
            kind = None
            if isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Attribute):
                tgt = node.target
                kind = "dec" if isinstance(node.op, ast.Sub) else "inc"
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        tgt, kind = t, "assign"
            if tgt is None or not (isinstance(tgt.value, ast.Name)
                                   and tgt.value.id == "self"):
                continue
            if exempt and kind != "dec":
                continue  # __init__/reset may (re)initialize, never decrement
            out.setdefault(tgt.attr, {"dec": [], "inc": [], "assign": []})[
                kind].append(node.lineno)

    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            exempt = node.name == "__init__" or node.name.startswith("reset")
            # only scan the function's own statements, not nested defs —
            # close enough for mutation bookkeeping
            scan_fn(node, exempt)
    return out


def check(index: RepoIndex) -> list[Finding]:
    findings: list[Finding] = []
    # name -> list[(type, file, line)]
    decls: dict[str, list[tuple[str, str, int]]] = {}
    all_samples: list[Sample] = []
    backings: dict[str, list[tuple[str, str, int]]] = {}  # name -> (file, attr, line)
    per_file_mutations: dict[str, dict] = {}

    for pf in index.files:
        types, samples, stats = _scan_file(pf)
        for name, kind, line in types:
            decls.setdefault(name, []).append((kind, pf.path, line))
        all_samples.extend(samples)
        for key, attr, line in stats:
            backings.setdefault(key, []).append((pf.path, attr, line))
        if types or samples or stats:
            per_file_mutations[pf.path] = _attr_mutations(pf)

    # -- type conflicts + naming ---------------------------------------------
    for name, entries in sorted(decls.items()):
        kinds = {k for k, _, _ in entries}
        if len(kinds) > 1:
            kind0, path0, line0 = entries[0]
            findings.append(Finding(
                RULE, path0, line0, "<metrics>", f"type-conflict:{name}",
                f"{name} is declared {' and '.join(sorted(kinds))} at "
                "different sites — one family, one TYPE",
            ))
            continue
        kind, path, line = entries[0]
        if kind == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                RULE, path, line, "<metrics>", f"counter-name:{name}",
                f"counter {name} does not end in _total — the convention "
                "every rate() dashboard query relies on",
            ))
        if kind == "gauge" and name.endswith("_total"):
            findings.append(Finding(
                RULE, path, line, "<metrics>", f"gauge-name:{name}",
                f"gauge {name} ends in _total — _total promises a "
                "monotonic counter; rename it or declare it counter",
            ))

    # -- counter decrement / inc-only gauges ----------------------------------
    checked_attrs: set = set()
    counter_names = {n for n, e in decls.items() if e[0][0] == "counter"
                     and len({k for k, _, _ in e}) == 1}
    gauge_names = {n for n, e in decls.items() if e[0][0] == "gauge"
                   and len({k for k, _, _ in e}) == 1}

    def attr_sites(name: str):
        """(file, attr, line) pairs backing a family, from sample f-strings
        and stats-dict literals (dict keys drop the vllm:/... prefix)."""
        out = []
        for s in all_samples:
            if s.name == name and s.value_attr:
                out.append((s.pf.path, s.value_attr, s.line))
        short = name.split(":", 1)[-1]
        for key in (name, short):
            out.extend(backings.get(key, []))
        return out

    for name in sorted(counter_names):
        for path, attr, line in attr_sites(name):
            if (path, attr) in checked_attrs:
                continue
            checked_attrs.add((path, attr))
            muts = per_file_mutations.get(path, {}).get(attr)
            if muts and muts["dec"]:
                findings.append(Finding(
                    RULE, path, muts["dec"][0], "<metrics>",
                    f"counter-decrement:{name}:{attr}",
                    f"{attr!r} backs counter {name} but is decremented — "
                    "counters only go up (reset=restart); decrementing "
                    "breaks rate()/increase()",
                ))
    gauge_checked: set = set()
    for name in sorted(gauge_names):
        for path, attr, line in attr_sites(name):
            if (path, attr) in gauge_checked:
                continue  # one finding per backing attr, not per sample site
            gauge_checked.add((path, attr))
            muts = per_file_mutations.get(path, {}).get(attr)
            if muts and muts["inc"] and not muts["assign"] and not muts["dec"]:
                findings.append(Finding(
                    RULE, path, muts["inc"][0], "<metrics>",
                    f"inc-only-gauge:{name}:{attr}",
                    f"{attr!r} backs gauge {name} but is only ever "
                    "incremented — that is a counter; rename *_total and "
                    "declare counter",
                ))

    # -- label keyset discipline ----------------------------------------------
    by_name: dict[str, list[Sample]] = {}
    for s in all_samples:
        by_name.setdefault(s.name, []).append(s)
    for name, samples in sorted(by_name.items()):
        for s in samples:
            if s.dynamic_label_key:
                findings.append(Finding(
                    RULE, s.pf.path, s.line, "<metrics>",
                    f"dynamic-label-key:{name}",
                    f"{name} renders a label KEY by interpolation — the "
                    "keyset must be closed literal text so the cardinality "
                    "guard can audit it",
                ))
        keysets = {s.labels for s in samples if s.labels is not None
                   and not s.dynamic_label_key}
        if len(keysets) > 1:
            anchor = samples[0]
            rendered = " vs ".join(
                "{" + ",".join(sorted(ks)) + "}" for ks in sorted(
                    keysets, key=lambda k: sorted(k))
            )
            findings.append(Finding(
                RULE, anchor.pf.path, anchor.line, "<metrics>",
                f"label-drift:{name}",
                f"{name} is rendered with different label keysets "
                f"({rendered}) — one family must keep one keyset or "
                "queries cannot join the series",
            ))

    # -- construct-once --------------------------------------------------------
    for pf in index.files:
        if pf.tree is None:
            continue
        for scope, node in _constructions(pf):
            findings.append(Finding(
                RULE, pf.path, node.lineno, scope,
                "construct-in-function:Histogram",
                "Histogram(...) constructed outside module scope/__init__ — "
                "a per-call family loses all history between scrapes",
            ))
    return findings


def _constructions(pf: PyFile):
    """Histogram() calls in non-__init__ function bodies."""
    def visit(node, scope, in_fn):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
                yield from visit(child, sub, child.name != "__init__")
            elif isinstance(child, ast.ClassDef):
                sub = f"{scope}.{child.name}" if scope else child.name
                yield from visit(child, sub, in_fn)
            else:
                if in_fn and isinstance(child, ast.Call):
                    fn = child.func
                    name = fn.id if isinstance(fn, ast.Name) else (
                        fn.attr if isinstance(fn, ast.Attribute) else None
                    )
                    if name == "Histogram":
                        yield scope, child
                yield from visit(child, scope, in_fn)
    if pf.tree is not None:
        yield from visit(pf.tree, "", False)
