"""GC007 — thread-ownership discipline for annotated state.

The engine is a two-context system (asyncio event loop + device thread),
and PR 10's migration review verified BY HAND that ``engine._frozen`` is
only ever touched on the device thread (freeze/commit/rollback all go
through ``_run_on_device_thread``). That reasoning was correct but lived
nowhere a refactor would trip over it. ``# owned-by:`` mechanizes it:

    self._frozen: dict = {}  # owned-by: device-thread

From then on, any access to ``._frozen`` — ANY receiver, ANY file in the
scan surface, so ``self.engine._frozen`` in migration/manager.py counts —
from a function whose execution context is lexically knowable and WRONG is
a violation:

- ``owned-by: device-thread`` state touched inside an ``async def``
  (event-loop context), or
- ``owned-by: event-loop`` state touched inside a function submitted to a
  worker (``threading.Thread`` target, ``run_in_executor`` /
  ``asyncio.to_thread`` / ``.submit`` / ``_run_on_device_thread`` callee),
- ``owned-by: any`` never flags — it documents deliberately free-threaded
  state (lock-free rings, atomic cursors).

Functions with UNKNOWN context (plain sync defs) are never flagged: a
helper may legitimately run in either context depending on its caller —
the submission sites are where the context is decided, and those are what
this checker reads. ``__init__`` and module top level are exempt
(initialization happens before a second context exists). Ownership is
claimed by ATTRIBUTE NAME across the surface — keep annotated names
distinctive; conflicting annotations drop the name from the cross-file
registry.
"""

from __future__ import annotations

import ast

from .core import Finding, RepoIndex, iter_nodes_skipping_nested_defs
from .ownership import (
    ANY,
    DEVICE,
    EVENT_LOOP,
    FileContexts,
    effective_tables,
    ownership_registry,
)

RULE = "GC007"


def _violates(owner: str, ctx: str) -> bool:
    if owner == ANY:
        return False
    if owner == DEVICE and ctx == EVENT_LOOP:
        return True
    if owner == EVENT_LOOP and ctx == DEVICE:
        return True
    return False


def check(index: RepoIndex) -> list[Finding]:
    all_attrs, all_globals, per_file = ownership_registry(index.files)
    if not all_attrs and not all_globals and not per_file:
        return []
    findings: list[Finding] = []
    for pf in index.files:
        if pf.tree is None:
            continue
        attrs, globals_ = effective_tables(
            all_attrs, all_globals, per_file, pf.path)
        fc = FileContexts(pf)
        for scope, fn in fc.iter_defs():
            if getattr(fn, "name", "") == "__init__":
                continue  # pre-thread initialization
            ctx = fc.context_of(fn)
            if ctx is None:
                continue
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            reported: set = set()
            for node in iter_nodes_skipping_nested_defs(body):
                attr = owner = None
                if isinstance(node, ast.Attribute) and node.attr in attrs:
                    attr, owner = node.attr, attrs[node.attr]
                elif isinstance(node, ast.Name) and node.id in globals_:
                    # module globals are annotated as bare names
                    attr, owner = node.id, globals_[node.id]
                if attr is None:
                    continue
                if not _violates(owner, ctx):
                    continue
                key = (attr, node.lineno)
                if key in reported:
                    continue
                reported.add(key)
                findings.append(Finding(
                    RULE, pf.path, node.lineno, scope,
                    f"off-context:{attr}@{ctx}",
                    f"{attr!r} is owned-by: {owner} but this code runs on "
                    f"the {ctx} — touch it from its owning context (the "
                    "engine idiom: submit via _run_on_device_thread / "
                    "run_in_executor, or marshal a copy)",
                ))
    return findings
