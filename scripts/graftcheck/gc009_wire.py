"""GC009 — wire-contract parity v2: frame ops, SSE control events, and
migration snapshot/meta keys.

GC005 guards HTTP route *paths*; everything else the fleet speaks is
stringly-typed too, and each surface has already drifted once by hand:

- **frame ops** — the cache server / transfer plane / KV controller all
  speak the kvoffload frame protocol, dispatching on ``op == "<literal>"``;
  clients build ``{"op": "<literal>", ...}`` headers. A server op no client
  sends is dead protocol; a client op no server handles is a runtime
  ``bad op`` error that only surfaces under load (PR 9/PR 10 added 16 ops
  across four client modules).
- **SSE control events** — the migration handoff rides ONE in-band event
  (``data: {"pstpu_migration": {...}}``); the router's splice keys on the
  event name and its payload keys (``target``, ``request_id``). A renamed
  key on either side silently breaks the splice and leaks the raw event to
  the client.
- **snapshot/meta keys** — ``SequenceSnapshot`` travels as a JSON doc whose
  producer (``to_doc``) and consumer (``from_doc``) key sets must match,
  and the presentation ``meta`` dict written at admission is read by the
  migration target and the fake engine; an unproduced-but-consumed key is
  a silent default on every migration.

Extraction is idiom-anchored (this is a repo-native checker, not a type
system): op dispatch = ``op == "..."`` comparisons; op sends = dict
literals with an ``"op"`` key; event producers = dict literals carrying the
event-type key (or stored into ``*._migrated_out[...]``); event consumers
= ``event.get("...")`` in the router's request_service; meta producers =
dict literals containing both ``"oid"`` and ``"chat"`` plus constant keys
added next to a ``**meta`` / ``**snap.meta`` spread; meta consumers =
``.get("...")``/``[...]`` on receivers whose text ends in ``meta``. The
tier-1 tests assert each extractor keeps seeing its real surface, so a
refactor cannot silently turn this rule into a vacuous pass.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from .core import Finding, PyFile, RepoIndex, expr_text

RULE = "GC009"

# frame-protocol servers: files that dispatch on `op == "..."`
SERVER_FILES = (
    "production_stack_tpu/kvoffload/cache_server.py",
    "production_stack_tpu/kvoffload/transfer.py",
    "production_stack_tpu/kvoffload/controller.py",
    "production_stack_tpu/kvfabric/server.py",
)
# SSE control-event surfaces
EVENT_PRODUCER_FILES = (
    "production_stack_tpu/engine/api_server.py",
    "production_stack_tpu/testing/fake_engine.py",
)
EVENT_CONSUMER_FILE = "production_stack_tpu/router/request_service.py"
# migration snapshot + presentation-meta surfaces
STATE_FILE = "production_stack_tpu/migration/state.py"
META_PRODUCER_FILES = EVENT_PRODUCER_FILES
META_CONSUMER_FILES = (
    "production_stack_tpu/engine/api_server.py",
    "production_stack_tpu/testing/fake_engine.py",
    "production_stack_tpu/migration/manager.py",
    "production_stack_tpu/migration/state.py",
    "production_stack_tpu/router/request_service.py",
)

_MARKER_KEY_RE = re.compile(r'\{"([A-Za-z0-9_]+)"')


# -- extraction: frame ops -----------------------------------------------------


def extract_handled_ops(pf: PyFile) -> dict[str, int]:
    """{op: line} for every ``op == "<literal>"`` dispatch comparison."""
    out: dict[str, int] = {}
    if pf.tree is None:
        return out
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Compare):
            continue
        if not (isinstance(node.left, ast.Name) and node.left.id == "op"):
            continue
        for cmp_op, comparator in zip(node.ops, node.comparators):
            if not isinstance(cmp_op, (ast.Eq, ast.In)):
                continue
            consts: list = []
            if isinstance(comparator, ast.Constant):
                consts = [comparator.value]
            elif isinstance(comparator, (ast.Tuple, ast.List, ast.Set)):
                consts = [e.value for e in comparator.elts
                          if isinstance(e, ast.Constant)]
            for v in consts:
                if isinstance(v, str) and v:
                    out.setdefault(v, node.lineno)
    return out


def extract_sent_ops(files: Iterable[PyFile]) -> dict[str, tuple[str, int]]:
    """{op: (file, line)} for every dict literal carrying an "op" key."""
    out: dict[str, tuple[str, int]] = {}
    for pf in files:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if (isinstance(k, ast.Constant) and k.value == "op"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)):
                    out.setdefault(v.value, (pf.path, node.lineno))
    return out


def check_frames(server_pfs: list[PyFile],
                 client_pfs: list[PyFile]) -> list[Finding]:
    handled: dict[str, tuple[str, int]] = {}
    for pf in server_pfs:
        for op, line in extract_handled_ops(pf).items():
            handled.setdefault(op, (pf.path, line))
    sent = extract_sent_ops(client_pfs)
    findings: list[Finding] = []
    for op, (path, line) in sorted(sent.items()):
        if op not in handled:
            findings.append(Finding(
                RULE, path, line, "<frames>", f"undeclared-op:{op}",
                f"client sends frame op {op!r} but no frame server "
                "dispatches on it — the peer will answer 'bad op' at "
                "runtime",
            ))
    for op, (path, line) in sorted(handled.items()):
        if op not in sent:
            findings.append(Finding(
                RULE, path, line, "<frames>", f"unconsumed-op:{op}",
                f"frame server handles op {op!r} but no client in the scan "
                "surface ever sends it — dead protocol (or the client "
                "moved without the server)",
            ))
    return findings


# -- extraction: SSE control events --------------------------------------------


def extract_event_consumer(pf: PyFile) -> "tuple[Optional[str], set, int]":
    """(event_type_key, consumed_payload_keys, anchor_line) from the
    router's splice: the marker byte-literal names the type key, and
    ``event.get("...")`` calls name the payload keys."""
    type_key: Optional[str] = None
    keys: set = set()
    line = 1
    if pf.tree is None:
        return None, keys, line
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
            m = _MARKER_KEY_RE.search(node.value.decode("utf-8", "replace"))
            if m and type_key is None:
                type_key = m.group(1)
                line = node.lineno
        # json.loads(payload)["<type key>"] — the parse-side key must agree
        elif isinstance(node, ast.Subscript):
            val = node.value
            if (isinstance(val, ast.Call)
                    and expr_text(val.func).endswith("json.loads")
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                if type_key is None:
                    type_key = node.slice.value
                    line = node.lineno
        elif isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "get"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in ("event", "next_event")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                keys.add(node.args[0].value)
    return type_key, keys, line


def extract_event_producers(files: Iterable[PyFile],
                            type_key: str) -> "tuple[set, dict]":
    """(payload_keys, sites): keys produced under the control-event type
    key — inline dict-literal values, plus dict literals stored into a
    ``*._migrated_out[...]`` subscript (the api_server indirection)."""
    keys: set = set()
    sites: dict[str, tuple[str, int]] = {}
    for pf in files:
        if pf.tree is None:
            continue
        produced_here = False
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and k.value == type_key:
                        produced_here = True
                        if isinstance(v, ast.Dict):
                            keys.update(
                                kk.value for kk in v.keys
                                if isinstance(kk, ast.Constant)
                            )
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Attribute)
                            and t.value.attr == "_migrated_out"
                            and isinstance(node.value, ast.Dict)):
                        produced_here = True
                        keys.update(
                            kk.value for kk in node.value.keys
                            if isinstance(kk, ast.Constant)
                        )
        if produced_here:
            sites[pf.path] = (pf.path, 1)
    return keys, sites


def check_events(producer_pfs: list[PyFile],
                 consumer_pf: PyFile) -> list[Finding]:
    type_key, consumed, line = extract_event_consumer(consumer_pf)
    if type_key is None:
        return []  # no splice in this surface — nothing to diff
    produced, sites = extract_event_producers(producer_pfs, type_key)
    findings: list[Finding] = []
    if not sites:
        findings.append(Finding(
            RULE, consumer_pf.path, line, "<events>",
            f"event-type-unproduced:{type_key}",
            f"the stream splice consumes control events typed {type_key!r} "
            "but no producer in the engine/fake surface emits that key — "
            "the splice can never trigger",
        ))
        return findings
    for k in sorted(consumed - produced):
        findings.append(Finding(
            RULE, consumer_pf.path, line, "<events>", f"event-key-unproduced:{k}",
            f"splice consumes control-event key {k!r} that no producer "
            "writes — it reads as None and the handoff aborts",
        ))
    for k in sorted(produced - consumed):
        src = sorted(sites)[0]
        findings.append(Finding(
            RULE, src, 1, "<events>", f"event-key-unconsumed:{k}",
            f"control-event key {k!r} is produced but the splice never "
            "reads it — producer/consumer drift (rename or dead field)",
        ))
    return findings


# -- extraction: snapshot doc + presentation meta ------------------------------


def extract_snapshot_keys(pf: PyFile) -> "tuple[set, set, int]":
    """(produced, consumed, line): dataclass fields + to_doc literal keys
    vs from_doc's ``doc[...]`` / ``doc.get(...)`` reads."""
    produced: set = set()
    consumed: set = set()
    line = 1
    if pf.tree is None:
        return produced, consumed, line
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "SequenceSnapshot":
            line = node.lineno
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name):
                    produced.add(stmt.target.id)
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Dict):
                            produced.update(
                                k.value for k in sub.keys
                                if isinstance(k, ast.Constant)
                                and isinstance(k.value, str)
                            )
                        if stmt.name == "from_doc":
                            _collect_reads(sub, "doc", consumed)
    return produced, consumed, line


def _collect_reads(node: ast.AST, recv: str, into: set) -> None:
    if isinstance(node, ast.Subscript):
        if (isinstance(node.value, ast.Name) and node.value.id == recv
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            into.add(node.slice.value)
    elif isinstance(node, ast.Call):
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "get"
                and isinstance(fn.value, ast.Name) and fn.value.id == recv
                and node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            into.add(node.args[0].value)


def check_snapshot(state_pf: PyFile) -> list[Finding]:
    produced, consumed, line = extract_snapshot_keys(state_pf)
    if not produced or not consumed:
        return []
    findings: list[Finding] = []
    for k in sorted(consumed - produced):
        findings.append(Finding(
            RULE, state_pf.path, line, "<snapshot>", f"snapshot-unproduced:{k}",
            f"from_doc reads snapshot key {k!r} that to_doc never writes",
        ))
    for k in sorted(produced - consumed):
        findings.append(Finding(
            RULE, state_pf.path, line, "<snapshot>", f"snapshot-unconsumed:{k}",
            f"snapshot key {k!r} is produced by to_doc but from_doc never "
            "reads it — wire drift (a migrated field silently drops)",
        ))
    return findings


def extract_meta_keys(producer_pfs: list[PyFile],
                      consumer_pfs: list[PyFile]) -> "tuple[set, set]":
    produced: set = set()
    consumed: set = set()
    for pf in producer_pfs:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Dict):
                continue
            const_keys = {k.value for k in node.keys
                          if isinstance(k, ast.Constant)
                          and isinstance(k.value, str)}
            if {"oid", "chat"} <= const_keys:
                produced.update(const_keys)  # the meta literal itself
                continue
            # augmentation: {**meta, "k": v} / {**snap.meta, "k": v}
            has_meta_spread = any(
                k is None and expr_text(v).endswith("meta")
                for k, v in zip(node.keys, node.values)
            )
            if has_meta_spread:
                produced.update(const_keys)
    for pf in consumer_pfs:
        if pf.tree is None:
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                if (isinstance(fn, ast.Attribute) and fn.attr == "get"
                        and expr_text(fn.value).endswith("meta")
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    consumed.add(node.args[0].value)
            elif isinstance(node, ast.Subscript):
                if (isinstance(node.value, ast.Attribute)
                        and node.value.attr == "meta"
                        and isinstance(node.slice, ast.Constant)
                        and isinstance(node.slice.value, str)):
                    consumed.add(node.slice.value)
    return produced, consumed


def check_meta(producer_pfs: list[PyFile],
               consumer_pfs: list[PyFile]) -> list[Finding]:
    produced, consumed = extract_meta_keys(producer_pfs, consumer_pfs)
    if not produced:
        return []
    anchor = producer_pfs[0]
    findings: list[Finding] = []
    for k in sorted(consumed - produced):
        findings.append(Finding(
            RULE, anchor.path, 1, "<meta>", f"meta-key-unproduced:{k}",
            f"migration presentation meta key {k!r} is consumed but never "
            "produced — every migrated stream silently falls back to the "
            "default",
        ))
    for k in sorted(produced - consumed):
        findings.append(Finding(
            RULE, anchor.path, 1, "<meta>", f"meta-key-unconsumed:{k}",
            f"migration presentation meta key {k!r} is produced but never "
            "consumed — dead wire field or a renamed consumer",
        ))
    return findings


# -- the real-tree gate --------------------------------------------------------


def check(index: RepoIndex) -> list[Finding]:
    findings: list[Finding] = []
    server_pfs = [pf for p in SERVER_FILES
                  if (pf := index.get(p)) is not None]
    if server_pfs:
        findings.extend(check_frames(server_pfs, index.files))
    consumer = index.get(EVENT_CONSUMER_FILE)
    producers = [pf for p in EVENT_PRODUCER_FILES
                 if (pf := index.get(p)) is not None]
    if consumer is not None and producers:
        findings.extend(check_events(producers, consumer))
    state_pf = index.get(STATE_FILE)
    if state_pf is not None:
        findings.extend(check_snapshot(state_pf))
    meta_consumers = [pf for p in META_CONSUMER_FILES
                      if (pf := index.get(p)) is not None]
    if producers and meta_consumers:
        findings.extend(check_meta(producers, meta_consumers))
    return findings
