"""graftcheck — repo-native static analysis for the hazard classes this
stack has actually shipped bugs in (docs/static-analysis.md).

Nine AST checkers plus an endpoint-contract guard, sharing one parsed view
of the tree (core.RepoIndex — same single-scan shape as
scripts/check_metrics_coverage.py):

- GC001 event-loop blocking: blocking primitives (time.sleep, sync file/
  HTTP/subprocess I/O, unbounded lock.acquire, jax.block_until_ready)
  reachable from an ``async def``, including one level of intra-package
  transitive calls. (PR 5's chaos harness found the router event loop wedged
  by exactly this — blocking log-pipe writes.)
- GC002 donation/aliasing safety: intra-function use of an array after it
  was passed at a donated argnum of a jitted callable, and operand reuse
  after a ``pallas_call`` with live ``input_output_aliases``. (PR 6's fused
  in-kernel KV write aliases the pools; seven donate_argnums sites in
  runner.py.)
- GC003 tracer/jit hygiene: Python branching, host conversions
  (float/int/bool/.item()/np.asarray), and logging/f-strings on traced
  values inside functions handed to jax.jit / lax.scan / Pallas — every one
  is a silent recompile or host sync (PR 7's vllm:compile_seconds_total
  exists to catch the aftermath).
- GC004 lock discipline: attributes annotated ``# guarded-by: <lock>`` may
  only be touched inside ``with <lock>`` (single-file scope; __init__ /
  module top level exempt as pre-thread initialization).
- GC005 endpoint-contract parity: every engine route the router names must
  exist on BOTH the real engine (api_server.py) and the fake engine
  (testing/fake_engine.py) — fake/real drift otherwise only surfaces as
  flaky e2e failures.
- GC006 asyncio task lifetime: every ``create_task``/``ensure_future``
  result must be retained (attribute, collection, awaited, or passed on) —
  the loop's weak refs let GC silently kill fire-and-forget tasks (the PR 9
  directory-persistence and fake-publish bugs).
- GC007 thread-ownership discipline: state annotated ``# owned-by:
  event-loop|device-thread|any`` may only be touched from its owning
  context; contexts are inferred from ``async def``, ``threading.Thread``
  targets, and executor/``to_thread``/``_run_on_device_thread`` submissions
  (mechanizes PR 10's hand-verified ``_frozen`` reasoning).
- GC008 off-context iteration/serialization: a loop-owned container handed
  into (or iterated/``json.dumps``-ed inside) worker-submitted code dies
  with 'dict changed size' under load — the PR 9 snapshot crash.
- GC009 wire-contract parity v2: cache-server frame ops vs client senders
  (both directions), the migration SSE control event's type + payload keys
  between engine/fake producers and the router splice, and
  snapshot/presentation-meta key sets — extracted from both sides, diffed.
- GC010 metric discipline: counter/gauge TYPE consistency and naming
  (``*_total``), no decremented counters, no inc-only gauges, metric
  objects constructed once, label keysets literal and consistent.

Suppression: ``# graftcheck: disable=GCnnn — <reason>`` on the finding's
line (or a standalone comment on the line above). The reason is mandatory,
and an unused suppression is itself a violation — same rot policy as the
metrics guard's allowlist. Pre-existing findings whose fix is not local live
in ``baseline.json`` with a mandatory justification; a baseline entry that
no longer matches a finding is rot and fails the guard.

Run: ``python -m scripts.graftcheck`` (pure ast — no JAX import), or through
tier-1 via tests/test_graftcheck.py.
"""

from .core import Finding, RepoIndex, run_graftcheck  # noqa: F401
