"""CLI: ``python -m scripts.graftcheck [--rule GCnnn] [--changed]
[--format sarif] [--output FILE] [--all-findings]``.

Exit 0 when the tree has zero unsuppressed, un-baselined findings (the
tier-1 contract tests/test_graftcheck.py enforces); exit 1 with a report
otherwise. Pure ast — no JAX import — so it runs as a fast standalone CI
step next to check_metrics_coverage.py.

``--changed`` is the pre-commit mode: findings are filtered to files the
git working tree/index touches (contract rules GC005/GC009/GC010 always
report in full — a drift can sit on the unchanged side of a diff), and an
empty change set passes without scanning. Falls back to the full tree when
git or the repository index is unavailable. The FULL run stays the CI and
tier-1 gate.

``--format sarif`` renders SARIF 2.1.0 for GitHub code-scanning upload
(ci.yml), so findings become inline PR annotations; ``--output`` writes it
to a file while the human-readable report still goes to stdout.
"""

from __future__ import annotations

import argparse
import sys

from .core import (
    changed_paths,
    filter_changed,
    load_baseline,
    run_graftcheck,
    RepoIndex,
)


def _all_checkers() -> dict:
    from .core import _checkers

    return {c.RULE: c for c in _checkers()}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "graftcheck", description="repo-native static analysis "
        "(GC001 event-loop blocking, GC002 donation/aliasing, GC003 "
        "tracer hygiene, GC004 lock discipline, GC005 endpoint parity, "
        "GC006 task lifetime, GC007 thread ownership, GC008 off-loop "
        "serialization, GC009 wire-contract parity, GC010 metric "
        "discipline)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only these rule ids (repeatable), e.g. GC001")
    ap.add_argument("--changed", action="store_true",
                    help="pre-commit mode: report only findings on files "
                    "the git working tree/index touches (contract rules "
                    "GC005/GC009/GC010 still report in full); falls back "
                    "to the full tree when git is unavailable")
    ap.add_argument("--format", choices=("text", "sarif"), default="text",
                    dest="fmt",
                    help="report format; 'sarif' emits SARIF 2.1.0 for "
                    "GitHub code-scanning upload (PR annotations)")
    ap.add_argument("--output", default=None,
                    help="write the --format report to this file (the "
                    "human-readable summary still prints to stdout)")
    ap.add_argument("--all-findings", action="store_true",
                    help="also print findings silenced by suppressions/"
                    "baseline (audit view)")
    args = ap.parse_args(argv)

    checkers = None
    if args.rule:
        all_checkers = _all_checkers()
        unknown = [r for r in args.rule if r not in all_checkers]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}")
            return 2
        checkers = [all_checkers[r] for r in args.rule]

    if args.all_findings:
        index = RepoIndex()
        raw = []
        from .core import _checkers

        for c in (checkers if checkers is not None else _checkers()):
            raw.extend(c.check(index))
        for f in sorted(raw, key=lambda f: (f.path, f.line)):
            print(f.render())
        print(f"\n{len(raw)} raw finding(s) before suppression/baseline")
        return 0

    changed = None
    if args.changed:
        changed = changed_paths()
        if changed is not None and not changed:
            print("graftcheck: --changed: clean working tree, nothing to check")
            print("GRAFTCHECK PASSED")
            return 0
        if changed is None:
            print("graftcheck: --changed: git index unavailable, "
                  "falling back to the full tree")

    violations, stats = run_graftcheck(
        checkers=checkers, baseline=load_baseline(),
    )
    if changed is not None:
        full = len(violations)
        violations = filter_changed(violations, changed)
        stats["changed_files"] = len(changed)
        stats["filtered_out"] = full - len(violations)

    if args.fmt == "sarif":
        from .sarif import render_sarif

        sarif = render_sarif(violations, stats)
        if args.output:
            with open(args.output, "w", encoding="utf-8") as fh:
                fh.write(sarif)
        else:
            sys.stdout.write(sarif)
            return 1 if violations else 0

    print(
        f"graftcheck: {stats['files']} files, {stats['raw_findings']} raw, "
        f"{stats['suppressed']} suppressed, {stats['baselined']} baselined"
        + (f", changed-only view over {stats['changed_files']} changed "
           f"file(s) ({stats['filtered_out']} finding(s) elsewhere hidden)"
           if changed is not None else "")
    )
    if violations:
        print("GRAFTCHECK FAILED:")
        for f in sorted(violations, key=lambda f: (f.path, f.line)):
            print(f"  - {f.render()}")
        print(
            "\nFix the hazard, or silence it with a reasoned\n"
            "'# graftcheck: disable=GCnnn — <reason>' on the line (see\n"
            "docs/static-analysis.md for the suppression/baseline policy)."
        )
        return 1
    print("GRAFTCHECK PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
