"""CLI: ``python -m scripts.graftcheck [--rule GCnnn] [--all-findings]``.

Exit 0 when the tree has zero unsuppressed, un-baselined findings (the
tier-1 contract tests/test_graftcheck.py enforces); exit 1 with a report
otherwise. Pure ast — no JAX import — so it runs as a fast standalone CI
step next to check_metrics_coverage.py.
"""

from __future__ import annotations

import argparse
import sys

from .core import RepoIndex, load_baseline, run_graftcheck


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "graftcheck", description="repo-native static analysis "
        "(GC001 event-loop blocking, GC002 donation/aliasing, GC003 "
        "tracer hygiene, GC004 lock discipline, GC005 endpoint parity)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only these rule ids (repeatable), e.g. GC001")
    ap.add_argument("--all-findings", action="store_true",
                    help="also print findings silenced by suppressions/"
                    "baseline (audit view)")
    args = ap.parse_args(argv)

    checkers = None
    if args.rule:
        from . import (gc001_eventloop, gc002_donation, gc003_tracer,
                       gc004_locks, gc005_endpoints)

        all_checkers = {c.RULE: c for c in (
            gc001_eventloop, gc002_donation, gc003_tracer, gc004_locks,
            gc005_endpoints,
        )}
        unknown = [r for r in args.rule if r not in all_checkers]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}")
            return 2
        checkers = [all_checkers[r] for r in args.rule]

    if args.all_findings:
        index = RepoIndex()
        raw = []
        from .core import _checkers

        for c in (checkers if checkers is not None else _checkers()):
            raw.extend(c.check(index))
        for f in sorted(raw, key=lambda f: (f.path, f.line)):
            print(f.render())
        print(f"\n{len(raw)} raw finding(s) before suppression/baseline")
        return 0

    violations, stats = run_graftcheck(
        checkers=checkers, baseline=load_baseline(),
    )
    print(
        f"graftcheck: {stats['files']} files, {stats['raw_findings']} raw, "
        f"{stats['suppressed']} suppressed, {stats['baselined']} baselined"
    )
    if violations:
        print("GRAFTCHECK FAILED:")
        for f in sorted(violations, key=lambda f: (f.path, f.line)):
            print(f"  - {f.render()}")
        print(
            "\nFix the hazard, or silence it with a reasoned\n"
            "'# graftcheck: disable=GCnnn — <reason>' on the line (see\n"
            "docs/static-analysis.md for the suppression/baseline policy)."
        )
        return 1
    print("GRAFTCHECK PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
