"""GC001 — event-loop blocking.

A blocking primitive anywhere under an ``async def`` stalls EVERY request the
loop is serving, not just the one that called it: PR 5's rolling-restart
chaos found the router wedged by blocking log-pipe writes, and PR 7 had to
move flight-recorder serialization off the loop (``dump_async``) for exactly
this reason. This checker flags the mechanically detectable core of that
class:

- direct blocking calls in an ``async def`` body (``time.sleep``, sync HTTP
  via ``requests``/``urllib``, ``subprocess``, builtin ``open``, unbounded
  ``lock.acquire()``, ``jax.block_until_ready``, ``os.system``), and
- ONE level of intra-package transitive calls: an ``async def`` calling a
  sync function (same module, same class, or an imported
  ``production_stack_tpu`` module) whose own body contains a blocking call.

Nested function definitions are skipped in both passes: a def nested inside
an async handler is almost always an executor thunk
(``asyncio.to_thread(_write)`` — the files-service pattern), which is the
CORRECT way to do blocking work. ``.acquire()`` is exempt when awaited
(asyncio locks) or called with ``blocking=False``/a ``timeout=``.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import (
    Finding,
    PyFile,
    RepoIndex,
    dotted_name,
    iter_nodes_skipping_nested_defs,
)

RULE = "GC001"

# dotted-call-name prefixes that block the calling thread
_BLOCKING_EXACT = {
    "time.sleep": "time.sleep blocks the event loop — use asyncio.sleep",
    "os.system": "os.system blocks the event loop",
    "socket.create_connection": "sync socket connect blocks the event loop",
    "urllib.request.urlopen": "sync HTTP (urllib) blocks the event loop",
    "jax.block_until_ready":
        "jax.block_until_ready stalls the loop on device completion",
}
_BLOCKING_PREFIX = {
    "requests.": "sync HTTP (requests) blocks the event loop",
    "subprocess.": "sync subprocess call blocks the event loop",
}
_BLOCKING_METHODS = {
    "block_until_ready":
        ".block_until_ready() stalls the loop on device completion",
}


def _blocking_reason(call: ast.Call, awaited: bool) -> Optional[tuple[str, str]]:
    """(detail, message) when `call` is a blocking primitive, else None."""
    name = dotted_name(call.func)
    if name is not None:
        if name in _BLOCKING_EXACT:
            return name, _BLOCKING_EXACT[name]
        for prefix, msg in _BLOCKING_PREFIX.items():
            if name.startswith(prefix):
                return name, msg
        if name == "open":
            return "open", (
                "builtin open() is sync file I/O — wrap in asyncio.to_thread"
            )
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in _BLOCKING_METHODS:
            return attr, _BLOCKING_METHODS[attr]
        if attr == "acquire" and not awaited:
            kw = {k.arg for k in call.keywords}
            has_bound = bool({"timeout", "blocking"} & kw) or call.args
            if not has_bound:
                return "acquire", (
                    "unbounded lock.acquire() can block the event loop "
                    "indefinitely — await an asyncio lock or bound it"
                )
    return None


def _blocking_in_body(fn: ast.AST) -> list[tuple[ast.Call, str, str]]:
    """Blocking calls directly in `fn`'s body (nested defs skipped).
    Returns (call_node, detail, message)."""
    out = []
    awaited_calls = set()
    for node in iter_nodes_skipping_nested_defs(fn.body):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            awaited_calls.add(id(node.value))
    for node in iter_nodes_skipping_nested_defs(fn.body):
        if isinstance(node, ast.Call):
            hit = _blocking_reason(node, awaited=id(node) in awaited_calls)
            if hit is not None:
                out.append((node, hit[0], hit[1]))
    return out


class _ModuleMaps:
    """Per-file resolution tables for one-level transitive calls."""

    def __init__(self, pf: PyFile, index: RepoIndex):
        self.functions: dict[str, ast.AST] = {}          # module-level defs
        self.methods: dict[tuple[str, str], ast.AST] = {}  # (class, name)
        self.imports: dict[str, str] = {}                # alias -> module
        self.from_imports: dict[str, tuple[str, str]] = {}  # name -> (mod, orig)
        if pf.tree is None:
            return
        for node in pf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.methods[(node.name, sub.name)] = sub
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (node.module, a.name)

    def resolve(self, call: ast.Call, cls: Optional[str],
                index: RepoIndex) -> Optional[tuple[ast.AST, str]]:
        """Resolve a call to an intra-package function def, one level deep.
        Returns (def_node, display_name) or None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            if fn.id in self.functions:
                return self.functions[fn.id], fn.id
            hit = self.from_imports.get(fn.id)
            if hit is not None:
                mod, orig = hit
                target = index.by_module.get(mod)
                if target is not None:
                    maps = _maps_for(target, index)
                    if orig in maps.functions:
                        return maps.functions[orig], f"{mod}.{orig}"
            return None
        if isinstance(fn, ast.Attribute):
            # self.method() / ClassName.method() in the same file
            if isinstance(fn.value, ast.Name):
                base = fn.value.id
                if base == "self" and cls is not None:
                    hit = self.methods.get((cls, fn.attr))
                    if hit is not None:
                        return hit, f"self.{fn.attr}"
                for (kls, name), node in self.methods.items():
                    if base == kls and name == fn.attr:
                        return node, f"{kls}.{fn.attr}"
                # imported_module.func()
                mod = self.imports.get(base)
                if mod is None and base in self.from_imports:
                    sub_mod, orig = self.from_imports[base]
                    mod = f"{sub_mod}.{orig}"
                if mod is not None:
                    target = index.by_module.get(mod)
                    if target is not None:
                        maps = _maps_for(target, index)
                        if fn.attr in maps.functions:
                            return maps.functions[fn.attr], f"{mod}.{fn.attr}"
        return None


_maps_cache: dict[str, _ModuleMaps] = {}


def _maps_for(pf: PyFile, index: RepoIndex) -> _ModuleMaps:
    maps = _maps_cache.get(pf.path)
    if maps is None:
        maps = _maps_cache[pf.path] = _ModuleMaps(pf, index)
    return maps


def check(index: RepoIndex) -> list[Finding]:
    _maps_cache.clear()
    findings: list[Finding] = []
    for pf in index.files:
        if pf.tree is None:
            continue
        maps = _maps_for(pf, index)
        # every async def, wherever it nests
        for scope, node in _async_defs(pf.tree):
            cls = scope.split(".")[-2] if "." in scope else None
            # direct blocking calls
            for call, detail, msg in _blocking_in_body(node):
                findings.append(Finding(
                    RULE, pf.path, call.lineno, scope, detail,
                    f"{msg} (in async def {node.name})",
                ))
            # one-level transitive: sync callee with a blocking body
            for sub in iter_nodes_skipping_nested_defs(node.body):
                if not isinstance(sub, ast.Call):
                    continue
                resolved = maps.resolve(sub, cls, index)
                if resolved is None:
                    continue
                callee, display = resolved
                if isinstance(callee, ast.AsyncFunctionDef):
                    continue  # awaited coroutine — its own body is checked
                for _, detail, msg in _blocking_in_body(callee):
                    findings.append(Finding(
                        RULE, pf.path, sub.lineno, scope,
                        f"{detail} via {display}",
                        f"{msg} — reached through sync call {display}() "
                        f"from async def {node.name}",
                    ))
    return findings


def _async_defs(tree: ast.Module):
    """(dotted scope, AsyncFunctionDef) pairs, at any nesting depth."""
    def visit(node, scope):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                sub = f"{scope}.{child.name}" if scope else child.name
                if isinstance(child, ast.AsyncFunctionDef):
                    yield sub, child
                yield from visit(child, sub)
            else:
                yield from visit(child, scope)
    yield from visit(tree, "")
