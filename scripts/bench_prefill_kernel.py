"""Prefill attention: Pallas kernel vs XLA gather+scan on the real chip.

Measures one layer's attention (the unit the kernel replaces) at QA-workload
shapes: a chunk of T fresh tokens attending over a long paged history.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.ops.attention import (
    flash_attention,
    gather_kv_pages,
    stale_kv_positions,
)
from production_stack_tpu.ops.pallas.prefill_attention import (
    ragged_paged_attention_prefill,
)
from production_stack_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".cache", "xla")
)

NH, KH, D, page = 32, 8, 64, 64


@jax.jit
def xla_path(q, kp, vp, pt, pos, lens, kc, vc):
    kg, vg = gather_kv_pages(kp, vp, pt)
    kv_pos = stale_kv_positions(pt, pos, page)
    k = jnp.concatenate([kg, kc], axis=1)
    v = jnp.concatenate([vg, vc], axis=1)
    return flash_attention(q, k, v, q_positions=pos, kv_lens=lens,
                           kv_positions=kv_pos)


def run(B, T, ctx_tokens, iters=20):
    rng = np.random.RandomState(0)
    maxp = ctx_tokens // page
    P = B * maxp + 1
    q = jnp.asarray(rng.randn(B, T, NH, D), jnp.bfloat16)
    kp = jnp.asarray(rng.randn(P, page, KH, D), jnp.bfloat16)
    vp = jnp.asarray(rng.randn(P, page, KH, D), jnp.bfloat16)
    kc = jnp.asarray(rng.randn(B, T, KH, D), jnp.bfloat16)
    vc = jnp.asarray(rng.randn(B, T, KH, D), jnp.bfloat16)
    pt = jnp.asarray(np.arange(B * maxp).reshape(B, maxp), jnp.int32)
    computed = ctx_tokens - T
    pos = jnp.asarray(
        np.arange(computed, computed + T)[None].repeat(B, 0), jnp.int32
    )
    lens = jnp.full((B,), ctx_tokens, jnp.int32)
    cl = jnp.full((B,), T, jnp.int32)

    def timeit(fn):
        np.asarray(fn())  # compile
        np.asarray(fn())
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        np.asarray(out)
        return (time.perf_counter() - t0) / iters * 1000

    t_xla = timeit(lambda: xla_path(q, kp, vp, pt, pos, lens, kc, vc))
    t_ker = timeit(lambda: ragged_paged_attention_prefill(
        q, kp, vp, pt, pos, lens, kc, vc, cl
    ))
    flops = 4 * B * T * ctx_tokens * NH * D  # QK^T + PV (causal ~ upper bound)
    print(
        f"B={B} T={T} ctx={ctx_tokens}: xla {t_xla:.2f} ms, "
        f"kernel {t_ker:.2f} ms ({t_xla / t_ker:.2f}x), "
        f"kernel {flops / (t_ker / 1e3) / 1e12:.1f} TFLOP/s"
    )
    # correctness on-chip
    ref = np.asarray(
        xla_path(q, kp, vp, pt, pos, lens, kc, vc), np.float32
    )
    out = np.asarray(ragged_paged_attention_prefill(
        q, kp, vp, pt, pos, lens, kc, vc, cl
    ), np.float32)
    err = np.max(np.abs(ref - out))
    print(f"  max |diff| = {err:.4f}")


if __name__ == "__main__":
    run(B=1, T=1024, ctx_tokens=16384)
    run(B=1, T=1024, ctx_tokens=8192)
    run(B=4, T=256, ctx_tokens=8192)
    run(B=1, T=1024, ctx_tokens=2048)
