"""Per-phase latency report from a /v1/traces JSON export.

Reads one or more trace exports (the payload of ``GET /v1/traces`` on the
router or engine — or both, merged: the two halves of a routed request share
one trace id) and renders a self-time attribution table: for every span name,
how much wall time the stack spent IN that phase, excluding time attributed to
its child spans. Self-times of a well-formed trace sum to the root span's
duration, so gaps (network hops, scheduling turnaround) surface as parent
self-time instead of silently vanishing — exactly the property the old
two-pass engine-direct benchmark contrast lacked.

Usage:
    curl -s $ROUTER/v1/traces > r.json
    curl -s $ENGINE/v1/traces > e.json
    python scripts/trace_report.py r.json e.json

Cross-link mode (the "why was this request slow" one-liner): given a trace
id and a flight-recorder export (``GET /v1/debug/flightrecorder`` on the
engine, or an anomaly dump file), render the trace's spans interleaved
chronologically with the engine events from the matching window — the
scheduler dispatches, KV evictions/restores, sheds, and compiles that
surrounded the request:

    curl -s $ENGINE/v1/debug/flightrecorder > fr.json
    python scripts/trace_report.py r.json e.json \
        --flightrecorder fr.json --trace-id <32-hex id>

``bench.py`` imports ``merge_exports`` / ``phase_table`` / ``render_table``
to emit the same attribution from its in-run trace scrapes.
"""

from __future__ import annotations

import argparse
import json
from typing import Iterable, Optional


def _spans_of(export) -> list[dict]:
    """Accept a /v1/traces export, a {"traces": [...]} dict, a list of trace
    groups, or a bare span list."""
    if isinstance(export, dict):
        export = export.get("traces", [])
    spans: list[dict] = []
    for item in export:
        if isinstance(item, dict) and "spans" in item:
            spans.extend(item["spans"])
        elif isinstance(item, dict):
            spans.append(item)
    return spans


def merge_exports(*exports) -> dict[str, list[dict]]:
    """Merge exports (possibly from different processes) into
    {trace_id: [span, ...]}, deduped by span id."""
    by_trace: dict[str, dict[str, dict]] = {}
    for ex in exports:
        for s in _spans_of(ex):
            by_trace.setdefault(s["trace_id"], {})[s["span_id"]] = s
    return {t: list(ss.values()) for t, ss in by_trace.items()}


def _percentile(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(len(s) * q))]


def trace_breakdown(spans: list[dict]) -> Optional[dict]:
    """One trace's attribution: root duration, per-name self time, and the
    share of the root covered by leaf phases."""
    if not spans:
        return None
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str, list[dict]] = {}
    roots = []
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    root = max(roots, key=lambda s: s.get("duration_ms", 0.0))
    # Restrict accounting to the chosen root's subtree: a partial trace
    # (span ring wrapped mid-trace, or router/engine export windows
    # misaligned across pods) can carry orphan chains whose parents were
    # lost; counting those would push shares and leaf coverage past 100%
    # and silently corrupt the table.
    subtree: list[dict] = []
    stack = [root]
    while stack:
        s = stack.pop()
        subtree.append(s)
        stack.extend(children.get(s["span_id"], []))
    self_ms: dict[str, float] = {}
    leaf_ms = 0.0
    for s in subtree:
        kids = children.get(s["span_id"], [])
        own = max(
            0.0,
            s.get("duration_ms", 0.0) - sum(k.get("duration_ms", 0.0) for k in kids),
        )
        self_ms[s["name"]] = self_ms.get(s["name"], 0.0) + own
        if not kids:
            leaf_ms += s.get("duration_ms", 0.0)
    e2e = root.get("duration_ms", 0.0)
    return {
        "trace_id": root["trace_id"],
        "root": root["name"],
        "e2e_ms": e2e,
        "self_ms": self_ms,
        "leaf_coverage": (leaf_ms / e2e) if e2e > 0 else 0.0,
    }


def phase_table(merged: dict[str, list[dict]]) -> dict:
    """Aggregate attribution across traces.

    Returns {"phases": {name: {count, p50_self_ms, p99_self_ms, total_ms,
    share}}, "traces": N, "e2e_p50_ms": ..., "leaf_coverage_p50": ...} where
    ``share`` is the phase's fraction of total root wall time."""
    per_name: dict[str, list[float]] = {}
    e2es: list[float] = []
    coverages: list[float] = []
    for spans in merged.values():
        b = trace_breakdown(spans)
        if b is None or b["e2e_ms"] <= 0:
            continue
        e2es.append(b["e2e_ms"])
        coverages.append(b["leaf_coverage"])
        for name, ms in b["self_ms"].items():
            per_name.setdefault(name, []).append(ms)
    total_e2e = sum(e2es)
    phases = {}
    for name, vals in sorted(
        per_name.items(), key=lambda kv: -sum(kv[1])
    ):
        total = sum(vals)
        phases[name] = {
            "count": len(vals),
            "p50_self_ms": round(_percentile(vals, 0.5), 2),
            "p99_self_ms": round(_percentile(vals, 0.99), 2),
            "total_ms": round(total, 2),
            "share": round(total / total_e2e, 4) if total_e2e else 0.0,
        }
    return {
        "phases": phases,
        "traces": len(e2es),
        "e2e_p50_ms": round(_percentile(e2es, 0.5), 2),
        "leaf_coverage_p50": round(_percentile(coverages, 0.5), 4),
    }


def render_table(table: dict) -> str:
    lines = [
        f"traces: {table['traces']}   e2e p50: {table['e2e_p50_ms']} ms   "
        f"leaf-phase coverage p50: {table['leaf_coverage_p50']:.1%}",
        f"{'phase':<28} {'count':>6} {'p50 self ms':>12} "
        f"{'p99 self ms':>12} {'share':>7}",
    ]
    for name, row in table["phases"].items():
        lines.append(
            f"{name:<28} {row['count']:>6} {row['p50_self_ms']:>12.2f} "
            f"{row['p99_self_ms']:>12.2f} {row['share']:>6.1%}"
        )
    return "\n".join(lines)


# -- cross-link mode (trace spans x flight-recorder events) -------------------


def _recorder_events(export) -> list[dict]:
    """Accept a /v1/debug/flightrecorder export, an anomaly dump, or a bare
    event list."""
    if isinstance(export, dict):
        export = export.get("events", [])
    return [e for e in export if isinstance(e, dict) and "kind" in e]


def _event_line(ev: dict) -> str:
    d = ev.get("data") or {}
    kind = ev["kind"]
    if kind == "sched":
        gate = d.get("gate") or {}
        detail = (
            f"{d.get('batch_kind')} rows={d.get('rows')} "
            f"bursts={d.get('bursts')} chunk_tokens={d.get('chunk_tokens')} "
            f"waiting={d.get('waiting')} alternate={gate.get('alternate')}"
        )
    elif kind == "step":
        detail = (
            f"{d.get('batch_kind')} wall={d.get('wall_ms')}ms "
            f"fetched={d.get('fetched')}"
        )
    elif kind == "kv":
        detail = " ".join(
            f"{k}={v}" for k, v in d.items() if k != "victim_scores"
        )
    else:
        detail = " ".join(f"{k}={v}" for k, v in sorted(d.items()))
    return f"event  {kind:<8} step={ev.get('step', -1):<6} {detail}"


def crosslink_report(
    merged: dict[str, list[dict]],
    recorder_export,
    trace_id: str,
    window_slack_s: float = 1.0,
) -> str:
    """Render one trace's spans interleaved (chronologically, by wall-clock
    start) with the flight-recorder events of the matching window: events
    stamped with the trace id itself, plus every event inside the trace's
    [start - slack, end + slack] wall window — the dispatches that served
    OTHER requests in between are exactly what explains a queue-shaped gap."""
    spans = merged.get(trace_id)
    if not spans:
        return f"trace {trace_id} not found in the supplied exports"
    events = _recorder_events(recorder_export)
    t0 = min(s["start"] for s in spans)
    t1 = max(s["start"] + s.get("duration_ms", 0.0) / 1000 for s in spans)
    window = [
        e for e in events
        if e.get("trace_id") == trace_id
        or (t0 - window_slack_s) <= e.get("t", 0.0) <= (t1 + window_slack_s)
    ]
    rows: list[tuple[float, str]] = []
    for s in sorted(spans, key=lambda s: s["start"]):
        rows.append((
            s["start"],
            f" span  {s['name']:<26} +{(s['start'] - t0) * 1000:8.1f}ms "
            f"dur={s.get('duration_ms', 0.0):.1f}ms",
        ))
    for e in window:
        linked = "*" if e.get("trace_id") == trace_id else " "
        rows.append((
            e.get("t", t0),
            f"{linked}{_event_line(e)}  +{(e.get('t', t0) - t0) * 1000:.1f}ms",
        ))
    rows.sort(key=lambda r: r[0])
    linked_n = sum(1 for e in window if e.get("trace_id") == trace_id)
    head = (
        f"trace {trace_id}: {len(spans)} spans over "
        f"{(t1 - t0) * 1000:.1f} ms; {len(window)} engine events in window "
        f"({linked_n} cross-linked by trace id; * marks them)"
    )
    return "\n".join([head] + [r[1] for r in rows])


def report(paths: Iterable[str]) -> str:
    exports = []
    for p in paths:
        with open(p) as f:
            exports.append(json.load(f))
    return render_table(phase_table(merge_exports(*exports)))


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Render a per-phase latency table from /v1/traces exports"
    )
    ap.add_argument("paths", nargs="+", help="JSON export file(s); exports "
                    "from router and engine merge by trace id")
    ap.add_argument("--flightrecorder", default=None,
                    help="flight-recorder export or anomaly dump (JSON); "
                         "with --trace-id, renders the trace's spans "
                         "interleaved with the matching engine-event window")
    ap.add_argument("--trace-id", default=None,
                    help="32-hex trace id for cross-link mode")
    args = ap.parse_args()
    if args.flightrecorder or args.trace_id:
        if not (args.flightrecorder and args.trace_id):
            ap.error("cross-link mode needs BOTH --flightrecorder and --trace-id")
        exports = []
        for p in args.paths:
            with open(p) as f:
                exports.append(json.load(f))
        with open(args.flightrecorder) as f:
            recorder = json.load(f)
        print(crosslink_report(merge_exports(*exports), recorder, args.trace_id))
        return
    print(report(args.paths))


if __name__ == "__main__":
    main()
