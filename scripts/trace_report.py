"""Per-phase latency report from a /v1/traces JSON export.

Reads one or more trace exports (the payload of ``GET /v1/traces`` on the
router or engine — or both, merged: the two halves of a routed request share
one trace id) and renders a self-time attribution table: for every span name,
how much wall time the stack spent IN that phase, excluding time attributed to
its child spans. Self-times of a well-formed trace sum to the root span's
duration, so gaps (network hops, scheduling turnaround) surface as parent
self-time instead of silently vanishing — exactly the property the old
two-pass engine-direct benchmark contrast lacked.

Usage:
    curl -s $ROUTER/v1/traces > r.json
    curl -s $ENGINE/v1/traces > e.json
    python scripts/trace_report.py r.json e.json

``bench.py`` imports ``merge_exports`` / ``phase_table`` / ``render_table``
to emit the same attribution from its in-run trace scrapes.
"""

from __future__ import annotations

import argparse
import json
from typing import Iterable, Optional


def _spans_of(export) -> list[dict]:
    """Accept a /v1/traces export, a {"traces": [...]} dict, a list of trace
    groups, or a bare span list."""
    if isinstance(export, dict):
        export = export.get("traces", [])
    spans: list[dict] = []
    for item in export:
        if isinstance(item, dict) and "spans" in item:
            spans.extend(item["spans"])
        elif isinstance(item, dict):
            spans.append(item)
    return spans


def merge_exports(*exports) -> dict[str, list[dict]]:
    """Merge exports (possibly from different processes) into
    {trace_id: [span, ...]}, deduped by span id."""
    by_trace: dict[str, dict[str, dict]] = {}
    for ex in exports:
        for s in _spans_of(ex):
            by_trace.setdefault(s["trace_id"], {})[s["span_id"]] = s
    return {t: list(ss.values()) for t, ss in by_trace.items()}


def _percentile(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(len(s) * q))]


def trace_breakdown(spans: list[dict]) -> Optional[dict]:
    """One trace's attribution: root duration, per-name self time, and the
    share of the root covered by leaf phases."""
    if not spans:
        return None
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str, list[dict]] = {}
    roots = []
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    root = max(roots, key=lambda s: s.get("duration_ms", 0.0))
    # Restrict accounting to the chosen root's subtree: a partial trace
    # (span ring wrapped mid-trace, or router/engine export windows
    # misaligned across pods) can carry orphan chains whose parents were
    # lost; counting those would push shares and leaf coverage past 100%
    # and silently corrupt the table.
    subtree: list[dict] = []
    stack = [root]
    while stack:
        s = stack.pop()
        subtree.append(s)
        stack.extend(children.get(s["span_id"], []))
    self_ms: dict[str, float] = {}
    leaf_ms = 0.0
    for s in subtree:
        kids = children.get(s["span_id"], [])
        own = max(
            0.0,
            s.get("duration_ms", 0.0) - sum(k.get("duration_ms", 0.0) for k in kids),
        )
        self_ms[s["name"]] = self_ms.get(s["name"], 0.0) + own
        if not kids:
            leaf_ms += s.get("duration_ms", 0.0)
    e2e = root.get("duration_ms", 0.0)
    return {
        "trace_id": root["trace_id"],
        "root": root["name"],
        "e2e_ms": e2e,
        "self_ms": self_ms,
        "leaf_coverage": (leaf_ms / e2e) if e2e > 0 else 0.0,
    }


def phase_table(merged: dict[str, list[dict]]) -> dict:
    """Aggregate attribution across traces.

    Returns {"phases": {name: {count, p50_self_ms, p99_self_ms, total_ms,
    share}}, "traces": N, "e2e_p50_ms": ..., "leaf_coverage_p50": ...} where
    ``share`` is the phase's fraction of total root wall time."""
    per_name: dict[str, list[float]] = {}
    e2es: list[float] = []
    coverages: list[float] = []
    for spans in merged.values():
        b = trace_breakdown(spans)
        if b is None or b["e2e_ms"] <= 0:
            continue
        e2es.append(b["e2e_ms"])
        coverages.append(b["leaf_coverage"])
        for name, ms in b["self_ms"].items():
            per_name.setdefault(name, []).append(ms)
    total_e2e = sum(e2es)
    phases = {}
    for name, vals in sorted(
        per_name.items(), key=lambda kv: -sum(kv[1])
    ):
        total = sum(vals)
        phases[name] = {
            "count": len(vals),
            "p50_self_ms": round(_percentile(vals, 0.5), 2),
            "p99_self_ms": round(_percentile(vals, 0.99), 2),
            "total_ms": round(total, 2),
            "share": round(total / total_e2e, 4) if total_e2e else 0.0,
        }
    return {
        "phases": phases,
        "traces": len(e2es),
        "e2e_p50_ms": round(_percentile(e2es, 0.5), 2),
        "leaf_coverage_p50": round(_percentile(coverages, 0.5), 4),
    }


def render_table(table: dict) -> str:
    lines = [
        f"traces: {table['traces']}   e2e p50: {table['e2e_p50_ms']} ms   "
        f"leaf-phase coverage p50: {table['leaf_coverage_p50']:.1%}",
        f"{'phase':<28} {'count':>6} {'p50 self ms':>12} "
        f"{'p99 self ms':>12} {'share':>7}",
    ]
    for name, row in table["phases"].items():
        lines.append(
            f"{name:<28} {row['count']:>6} {row['p50_self_ms']:>12.2f} "
            f"{row['p99_self_ms']:>12.2f} {row['share']:>6.1%}"
        )
    return "\n".join(lines)


def report(paths: Iterable[str]) -> str:
    exports = []
    for p in paths:
        with open(p) as f:
            exports.append(json.load(f))
    return render_table(phase_table(merge_exports(*exports)))


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Render a per-phase latency table from /v1/traces exports"
    )
    ap.add_argument("paths", nargs="+", help="JSON export file(s); exports "
                    "from router and engine merge by trace id")
    args = ap.parse_args()
    print(report(args.paths))


if __name__ == "__main__":
    main()
