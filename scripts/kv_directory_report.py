#!/usr/bin/env python3
"""Dump the fleet-wide KV directory from a live cache server.

Debugging surface for fleet-warm tests and production triage
(docs/kv-directory.md): prints per-engine residency (resident vs shared
chunk counts, generation, liveness), the resident chain-depth histogram,
and the staleness/expiry accounting — the numbers that tell you whether
KV-aware routing v2 is seeing the fleet you think it is.

Usage:
    python scripts/kv_directory_report.py --url 127.0.0.1:8200
    python scripts/kv_directory_report.py --url 127.0.0.1:8200 --json
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # runnable as a plain script from the repo root

from production_stack_tpu.kvoffload.protocol import BlockingClient, parse_hostport  # noqa: E402


def fetch(url: str, timeout: float = 10.0) -> dict:
    """One round trip each for the dump + raw stats (blob-map counters)."""
    host, port = parse_hostport(url, default_port=8200)
    client = BlockingClient(host, port, timeout=timeout)
    try:
        dump, _ = client.request({"op": "dir_dump"})
        stats, _ = client.request({"op": "stats"})
    finally:
        client.close()
    if not dump.get("ok"):
        raise RuntimeError(f"dir_dump failed: {dump.get('error')}")
    dump.pop("ok", None)
    dump["cache_server"] = {
        k: stats.get(k)
        for k in ("entries", "used_bytes", "max_bytes", "hits", "gets", "corrupt")
    }
    return dump


def _bar(n: int, scale: int, width: int = 40) -> str:
    return "#" * max(1 if n else 0, round(width * n / max(scale, 1)))


def render(dump: dict) -> str:
    lines = ["=== fleet-wide KV directory ==="]
    lines.append(
        f"entries={dump.get('kv_directory_entries', 0)} "
        f"chunks={dump.get('kv_directory_chunks', 0)} "
        f"engines={dump.get('kv_directory_engines', 0)}"
    )
    lines.append(
        f"publishes={dump.get('kv_directory_publishes_total', 0)} "
        f"withdrawals={dump.get('kv_directory_withdrawals_total', 0)} "
        f"stale_hits={dump.get('kv_directory_stale_hits_total', 0)} "
        f"expired={dump.get('kv_directory_expired_entries_total', 0)} "
        f"lookups={dump.get('kv_directory_lookups_total', 0)}"
    )
    cs = dump.get("cache_server") or {}
    lines.append(
        f"blob tier: {cs.get('entries', 0)} blobs, "
        f"{(cs.get('used_bytes') or 0) / 1e6:.1f} MB used, "
        f"{cs.get('corrupt', 0)} quarantined"
    )
    lines.append("")
    lines.append("--- per-engine residency ---")
    engines = dump.get("engines") or {}
    if not engines:
        lines.append("(no engines registered)")
    for url in sorted(engines):
        e = engines[url]
        lines.append(
            f"{url}: resident={e.get('resident_chunks', 0)} "
            f"shared={e.get('shared_chunks', 0)} "
            f"page_size={e.get('page_size', 0)} "
            f"generation={e.get('generation', 0)} "
            f"{'ALIVE' if e.get('alive') else 'EXPIRED (resident claims dropped)'}"
        )
    lines.append("")
    lines.append("--- resident chain-depth histogram ---")
    hist = dump.get("depth_histogram") or {}
    if not hist:
        lines.append("(no resident chunks)")
    else:
        peak = max(hist.values())
        for depth in sorted(hist, key=int):
            n = hist[depth]
            lines.append(f"depth {int(depth):4d}: {n:6d} {_bar(n, peak)}")
    return "\n".join(lines)


def main() -> int:
    p = argparse.ArgumentParser("kv-directory-report")
    p.add_argument("--url", default="127.0.0.1:8200",
                   help="cache server address hosting the directory")
    p.add_argument("--json", action="store_true",
                   help="raw JSON dump instead of the rendered report")
    args = p.parse_args()
    try:
        dump = fetch(args.url)
    except Exception as e:  # noqa: BLE001 - CLI surface
        print(f"kv_directory_report: cannot reach {args.url}: {e}")
        return 1
    if args.json:
        print(json.dumps(dump, indent=2, sort_keys=True))
    else:
        print(render(dump))
    return 0


if __name__ == "__main__":
    sys.exit(main())
