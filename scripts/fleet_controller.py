#!/usr/bin/env python3
"""Saturation-driven fleet controller CLI (ISSUE 10; docs/migration.md).

Runs the closed control loop in production_stack_tpu/migration/controller.py
as a standalone process — a prometheus-adapter-style sidecar that consumes
the stack's own telemetry (per-engine ``vllm:engine_saturated`` / queue
depth via ``/metrics``, ``vllm_router:fleet_saturation`` when a router URL
is given) and, instead of only *reporting* pressure, acts on it with live
sequence migration:

- steady-state loop: **rebalance** the hottest long streams off the most
  pressured engine onto the coolest one (hysteresis + cooldown +
  max-concurrent-migrations cap);
- ``--drain URL``: **evacuate** every migratable sequence off one engine and
  exit — run this before SIGTERM'ing the pod and scale-down drops zero
  streams (the chaos ``--scenario scale-cycle`` asserts exactly this);
- ``--once``: one decision tick (cron-style operation), print the actions.

Examples:

    python scripts/fleet_controller.py \
        --engines http://e0:8100,http://e1:8100 --router-url http://r:8000
    python scripts/fleet_controller.py --engines ... --drain http://e1:8100
    python scripts/fleet_controller.py --engines ... --once

``--metrics-port`` serves the controller's own Prometheus surface
(``vllm:fleet_controller_*``, see docs/migration.md).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

sys.path.insert(0, ".")

from production_stack_tpu.migration.controller import (  # noqa: E402
    ControllerPolicy,
    FleetController,
)
from production_stack_tpu.utils.logging import init_logger  # noqa: E402

logger = init_logger("fleet-controller")


def build_controller(args) -> FleetController:
    policy = ControllerPolicy(
        rebalance_high_delta=args.rebalance_high_delta,
        rebalance_low_delta=args.rebalance_low_delta,
        cooldown_s=args.cooldown,
        max_concurrent_migrations=args.max_concurrent_migrations,
        rebalance_k=args.rebalance_k,
        saturation_queue_ref=args.saturation_queue_ref,
        interactive_ttft_watermark_ms=args.interactive_ttft_watermark_ms,
        interactive_itl_watermark_ms=args.interactive_itl_watermark_ms,
        latency_release_ratio=args.latency_release_ratio,
        latency_protect_k=args.latency_protect_k,
    )
    return FleetController(
        engine_urls=[u for u in args.engines.split(",") if u],
        router_url=args.router_url,
        policy=policy,
        tick_interval_s=args.tick_interval,
    )


async def _serve_metrics(ctrl: FleetController, host: str, port: int):
    from aiohttp import web

    async def metrics(request):
        return web.Response(
            text=ctrl.metrics_text(), content_type="text/plain"
        )

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("fleet controller metrics on %s:%d", host, port)
    return runner


async def _run(args) -> int:
    ctrl = build_controller(args)
    try:
        if args.metrics_port:
            await _serve_metrics(ctrl, args.metrics_host, args.metrics_port)
        if args.drain:
            report = await ctrl.evacuate(
                args.drain.rstrip("/"), deadline_s=args.drain_deadline
            )
            print(json.dumps(report, indent=2))
            ok = (
                report["residual_running"] == 0
                and report["residual_migratable"] == 0
            )
            print("DRAIN " + ("COMPLETE" if ok else "INCOMPLETE"))
            return 0 if ok else 1
        if args.once:
            actions = await ctrl.tick()
            print(json.dumps(
                [a.__dict__ for a in actions], indent=2
            ))
            return 0
        from production_stack_tpu.utils.signals import wait_for_termination

        stop = asyncio.Event()
        loop_task = asyncio.create_task(ctrl.run(stop))
        await wait_for_termination()
        stop.set()
        await loop_task
        return 0
    finally:
        await ctrl.close()


def main() -> int:
    p = argparse.ArgumentParser("fleet-controller")
    p.add_argument("--engines", required=True,
                   help="comma-separated engine base URLs the controller "
                        "scrapes and migrates between")
    p.add_argument("--router-url", default=None,
                   help="router base URL; its vllm_router:fleet_saturation "
                        "gauge becomes the fleet pressure signal (default: "
                        "mean per-engine pressure)")
    p.add_argument("--tick-interval", type=float, default=5.0,
                   help="seconds between control-loop ticks")
    p.add_argument("--rebalance-high-delta", type=float, default=0.5,
                   help="hottest-minus-coolest pressure delta that ENGAGES "
                        "rebalancing (hysteresis high watermark)")
    p.add_argument("--rebalance-low-delta", type=float, default=0.2,
                   help="pressure delta below which rebalancing disengages "
                        "(hysteresis low watermark)")
    p.add_argument("--cooldown", type=float, default=10.0,
                   help="seconds between controller actions of one kind")
    p.add_argument("--max-concurrent-migrations", type=int, default=2,
                   help="fleet-wide cap on migrations in flight")
    p.add_argument("--rebalance-k", type=int, default=1,
                   help="streams moved per rebalance decision (longest "
                        "output first)")
    p.add_argument("--saturation-queue-ref", type=int, default=8,
                   help="queue depth that scores a backend's pressure as "
                        "1.0 (the router's --saturation-queue-ref twin)")
    p.add_argument("--interactive-ttft-watermark-ms", type=float, default=0.0,
                   help="interactive-class TTFT p99 (vllm:interactive_"
                        "ttft_p99_ms) above which batch streams migrate "
                        "off the engine (latency_protect); 0 disables")
    p.add_argument("--interactive-itl-watermark-ms", type=float, default=0.0,
                   help="interactive-class inter-token p99 watermark for "
                        "latency_protect; 0 disables")
    p.add_argument("--latency-release-ratio", type=float, default=0.7,
                   help="latency_protect disengages when the breached p99 "
                        "falls below watermark * this ratio (hysteresis)")
    p.add_argument("--latency-protect-k", type=int, default=1,
                   help="batch streams moved per latency_protect decision")
    p.add_argument("--drain", default=None,
                   help="evacuate every migratable sequence off this engine "
                        "URL (zero-loss scale-down), print a report, exit")
    p.add_argument("--drain-deadline", type=float, default=60.0,
                   help="seconds --drain may spend evacuating")
    p.add_argument("--once", action="store_true",
                   help="run one decision tick and exit")
    p.add_argument("--metrics-host", default="0.0.0.0")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve GET /metrics (vllm:fleet_controller_*) on "
                        "this port; 0 disables")
    args = p.parse_args()
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
