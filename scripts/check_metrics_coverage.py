#!/usr/bin/env python3
"""Tier-1 guard: every Prometheus metric the stack emits must be visible.

PRs 2-6 each hand-added Grafana panels for their new metrics and nothing
caught a forgotten one — a metric nobody can see might as well not exist.
This guard statically extracts every ``vllm:`` / ``vllm_router:`` / ``fake:``
metric name emitted by the code and asserts each one is

1. **documented** — the name appears somewhere under ``docs/`` (the metrics
   reference table in docs/observability.md is the canonical home), and
2. **dashboarded** — the name appears in a Grafana dashboard
   (observability/tpu-stack-dashboard.json or the KV-offload dashboard
   ConfigMap), unless it is in ``DASHBOARD_ALLOWLIST`` (metrics that are
   intentionally scrape-only: debug/bench surfaces, redundant aliases,
   per-process internals).

Extraction is intentionally layered, because not every emitted name is a
single string literal:

- full-name literals anywhere under ``production_stack_tpu/`` (skipping
  f-string prefixes — a match immediately followed by ``{``);
- ``emit("<name>", ...)`` first arguments in engine/api_server.py (emitted
  under the ``vllm:`` namespace);
- the engine ``stats()`` dict keys the /metrics loop forwards with a
  ``vllm:`` prefix (``out["kv_*..."]`` in engine/engine.py, the
  ``warm_start_*`` keys in kvoffload/warmstart.py);
- ``GENERATED``: dynamic families built with f-strings (TTFT hop gauges,
  engine-loop section counters) that no literal scan can see. Adding a new
  dynamic family? List its expansion here or the guard cannot protect it.

Run standalone (``python scripts/check_metrics_coverage.py``) or through
tier-1 (tests/test_metrics_coverage.py). Exit code 1 + a report on gaps.
"""

from __future__ import annotations

import json
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

METRIC_RE = re.compile(r"(?:vllm|vllm_router|fake):[a-z][a-z0-9_]*[a-z0-9]")

# dynamic metric families (f-string built) -> concrete series names
GENERATED = [
    # engine/api_server.py: vllm:ttft_hop_{hop}_ms over the engine hops
    *(f"vllm:ttft_hop_{hop}_ms" for hop in (
        "accept_to_submit", "submit_to_first_token", "first_token_to_write",
        "admission_wait",
    )),
    # router/app.py via request_service.get_hop_quantiles(): router hops
    *(f"vllm_router:ttft_hop_{hop}_ms" for hop in (
        "recv_to_route", "route_to_connect", "connect_to_first_chunk",
    )),
    # engine/engine.py: loop_seconds sections -> vllm:engine_loop_*_seconds_total
    *(f"vllm:engine_loop_{sec}_seconds_total" for sec in (
        "wait", "schedule", "step", "apply", "emit", "chain_dispatch",
        "chain_fetch",
    )),
]

# intentionally NOT on a dashboard (documentation in docs/ is still
# mandatory). Keep each entry justified.
DASHBOARD_ALLOWLIST = {
    # redundant with the counters the dashboard derives rates from, or
    # debug-grade engine internals charted on demand, not by default
    "vllm:num_preemptions_total",
    "vllm:num_requests_swapped",
    "vllm:gpu_prefix_cache_hits_total",      # dashboard charts the rate gauge
    "vllm:gpu_prefix_cache_queries_total",
    "vllm:engine_loop_wait_seconds_total",   # loop-section breakdown is a
    "vllm:engine_loop_schedule_seconds_total",   # bench/debug surface
    "vllm:engine_loop_step_seconds_total",
    "vllm:engine_loop_apply_seconds_total",
    "vllm:engine_loop_emit_seconds_total",
    "vllm:engine_loop_chain_dispatch_seconds_total",
    "vllm:engine_loop_chain_fetch_seconds_total",
    "vllm:decode_dispatches_total",          # dispatch-shape bench telemetry
    "vllm:decode_chained_dispatches_total",
    "vllm:runahead_prefill_dispatches_total",
    "vllm:ttft_hop_accept_to_submit_ms",     # hop quantiles back bench
    "vllm:ttft_hop_submit_to_first_token_ms",    # attribution; the dashboard
    "vllm:ttft_hop_first_token_to_write_ms",     # charts the histograms
    "vllm:ttft_hop_admission_wait_ms",
    "vllm_router:ttft_hop_recv_to_route_ms",
    "vllm_router:ttft_hop_route_to_connect_ms",
    "vllm_router:ttft_hop_connect_to_first_chunk_ms",
    "vllm:spec_decode_num_draft_tokens_total",   # spec decode is off by
    "vllm:spec_decode_num_accepted_tokens_total",    # default (ROADMAP 5
    "vllm:spec_decode_draft_acceptance_rate",        # adds its panels)
    "vllm:kv_transfer_pinned_offer_bytes",   # leak probes for the transfer
    "vllm:kv_transfer_leaked_offers_total",  # sweep, asserted in tests
    "vllm:kv_transfer_cap_evicted_offers_total",
    "vllm:kv_offload_device_loaded_pages_total",  # disagg-only duplicate of
    "vllm:kv_transfer_received_chunks_total",     # the charted sent/chunks
    "vllm:kv_transfer_received_bytes_total",      # series
    "vllm:kv_offload_dropped_evictions_total",
    "vllm:warm_start_spilled_pages_total",   # dashboard charts restored +
    "vllm:warm_start_stale_manifests_skipped_total",  # age + generation
    "vllm:trace_spans_recorded_total",       # dashboard charts the dropped
    "vllm:trace_buffer_capacity",            # series; these are its context
    "vllm:flightrecorder_events_total",      # dashboard charts drops + dumps
    "vllm:flightrecorder_capacity",
    "vllm:flightrecorder_enabled",
    "vllm:tpu_hbm_bytes_limit",              # dashboard charts in_use vs
    "vllm:kv_pool_used_bytes",               # headroom; limits/pool are
    "vllm:kv_pool_device_bytes",             # their denominators
    "vllm:compile_events_total",             # dashboard charts the seconds
    "vllm:compile_cache_entries",
    "vllm:compile_cache_bytes",
    "vllm:engine_step_duty_cycle",
    "vllm_router:slo_request_outcomes_total",  # dashboard charts attainment
    "vllm_router:slo_records_total",           # these are its diagnostics
    "vllm_router:cpu_usage_perc",            # charted via the memory panel
    "vllm_router:num_swapped_requests",
    "vllm_router:avg_latency",               # dashboard charts the histogram
    # router-side mirrors of engine series the dashboard already charts
    # under their vllm: names (the mirrors exist so a router-only scrape
    # job still covers the fleet)
    "vllm_router:engine_running_requests",
    "vllm_router:engine_waiting_requests",
    "vllm_router:gpu_cache_usage_perc",
    "vllm_router:gpu_prefix_cache_hit_rate",
    "vllm_router:finished_requests",
    "vllm_router:time_to_first_token_seconds",   # dashboard heatmaps chart
    "vllm_router:e2e_request_latency_seconds",   # the engine-side histograms
    "vllm:kv_transfer_device_pages_total",   # device-path detail of the
                                             # charted chunks/s series
    # fake-engine-only observability: consumed by chaos assertions, never
    # deployed to a cluster with Grafana
    "fake:running_peak",
    "fake:served_total",
    "fake:completed_total",
    "fake:abort_requests_total",
    "fake:migrations_out_total",
    "fake:migrations_in_total",
    "fake:warm_prefetch_chunks",
    "fake:warm_prefix_hits_total",
    "fake:served_by_class_total",   # per-SLO-class split behind the chaos
    "fake:shed_by_class_total",     # batch-first shed assertions

    # fleet-controller diagnostics: the dashboard charts decisions-by-kind
    # and the saturation signal; started/failed/inflight are the drill-down
    # behind a decisions anomaly, charted on demand
    "vllm:fleet_controller_migrations_started_total",
    "vllm:fleet_controller_migrations_failed_total",
    "vllm:fleet_controller_migrations_inflight",
}


def _read(path: pathlib.Path) -> str:
    return path.read_text(encoding="utf-8", errors="replace")


def emitted_metrics() -> set[str]:
    names: set[str] = set()
    for path in (REPO / "production_stack_tpu").rglob("*.py"):
        text = _read(path)
        for m in METRIC_RE.finditer(text):
            end = m.end()
            # f-string family prefix ("vllm:ttft_hop_{hop}_ms"): covered by
            # GENERATED, the truncated literal is not a real series name
            if end < len(text) and text[end] in "{_":
                continue
            names.add(m.group(0))
    # engine /metrics emit("<name>", ...) -> vllm:<name>
    api = _read(REPO / "production_stack_tpu" / "engine" / "api_server.py")
    for m in re.finditer(r'emit\(\s*"([a-z0-9_]+)"', api):
        names.add(f"vllm:{m.group(1)}")
    # engine stats() dict keys the /metrics loop forwards under vllm:
    eng = _read(REPO / "production_stack_tpu" / "engine" / "engine.py")
    for m in re.finditer(
        r'out\["((?:kv_|spec_decode_|warm_start_)[a-z0-9_]+)"\]', eng
    ):
        names.add(f"vllm:{m.group(1)}")
    warm = _read(REPO / "production_stack_tpu" / "kvoffload" / "warmstart.py")
    for m in re.finditer(r'"(warm_start_[a-z0-9_]+)":', warm):
        names.add(f"vllm:{m.group(1)}")
    names.update(GENERATED)
    return names


_BRACE_RE = re.compile(
    r"((?:vllm|vllm_router|fake):[a-z0-9_]*)\{([a-z0-9_,]+)\}([a-z0-9_]*)"
)


def _expand_brace_families(text: str) -> str:
    """Docs may name metric families compactly —
    ``vllm:engine_loop_{wait,step}_seconds_total`` — one table row per
    family instead of seven near-identical ones. Append the expansions so
    the substring check sees every concrete series name."""
    extra = []
    for m in _BRACE_RE.finditer(text):
        for part in m.group(2).split(","):
            extra.append(f"{m.group(1)}{part}{m.group(3)}")
    return text + "\n" + "\n".join(extra)


def coverage_texts() -> tuple[str, str]:
    """(dashboard text, docs text) the names are checked against."""
    dashboards = _read(REPO / "observability" / "tpu-stack-dashboard.json")
    dashboards += _read(REPO / "observability" / "kvoffload-dashboard-cm.yaml")
    docs = "".join(
        _read(p) for p in sorted((REPO / "docs").glob("*.md"))
    )
    docs += _read(REPO / "README.md")
    return dashboards, _expand_brace_families(docs)


def check() -> list[str]:
    """Returns human-readable violations (empty = guard passes)."""
    dashboards, docs = coverage_texts()
    emitted = emitted_metrics()
    violations = []
    for name in sorted(emitted):
        missing = []
        if name not in docs:
            missing.append("docs/")
        if name not in dashboards and name not in DASHBOARD_ALLOWLIST:
            missing.append("dashboard")
        if missing:
            violations.append(f"{name}: not in {', '.join(missing)}")
    # allowlist hygiene: an entry for a metric nobody emits anymore is rot
    for name in sorted(DASHBOARD_ALLOWLIST - emitted):
        violations.append(f"{name}: allowlisted but not emitted (stale entry)")
    return violations


def main() -> int:
    names = emitted_metrics()
    violations = check()
    print(f"{len(names)} emitted metric names checked")
    if violations:
        print("METRICS COVERAGE FAILED:")
        for v in violations:
            print(f"  - {v}")
        print(
            "\nEvery emitted metric must appear in docs/ (the reference "
            "table in docs/observability.md) and in a Grafana dashboard "
            "(or scripts/check_metrics_coverage.py DASHBOARD_ALLOWLIST "
            "with a justification)."
        )
        return 1
    print("METRICS COVERAGE PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
