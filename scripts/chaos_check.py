#!/usr/bin/env python3
"""Chaos smoke for the router's failure-domain layer (docs/failure-handling.md).

Launches three fake engines — one ``--fail-rate 1.0`` (every request 500s),
one ``--hang`` (accepts requests, never responds), one healthy — behind a
router with retry/failover, a TTFT deadline, and passive circuit breaking
enabled, then drives a request run through the router and asserts:

- zero client-visible 5xx (every failure failed over to the healthy engine),
- no request consumed more proxy attempts than the retry budget (checked
  against the router's /v1/traces span export),
- both broken backends' circuit breakers are open by the end (checked
  against vllm_router:circuit_state on /metrics).

A second scenario, ``run_overload()`` (``--scenario overload``), drives an
arrival rate above fleet capacity: two fake engines with bounded admission
(``--saturate-after-n``) behind a shed-aware router. Overflow requests must
shed CLEANLY — every client response is a 200 or a 429 with Retry-After
(zero other errors, zero hangs), per-engine in-flight depth stays bounded,
and the shedding engines' circuit breakers stay closed (a shed is capacity,
not failure).

A third scenario, ``run_rolling_restart()`` (``--scenario rolling-restart``),
models a rolling upgrade: three engines behind a retry/breaker/health-check
router, restarted ONE AT A TIME (SIGTERM drain -> exit -> new process on the
same port, advertising a warm restore via ``--restart-restore-pages``) while
sustained client load runs throughout. Asserts zero client non-429 errors
across the whole rotation and that routed traffic RETURNS to each reborn
backend within the breaker half-open window (the reborn process's
``fake:served_total`` climbs from 0).

A fourth scenario, ``run_directory_restart()``
(``--scenario directory-restart``), exercises the fleet-wide KV directory
(ISSUE 9, docs/kv-directory.md): three fake engines publishing deterministic
chunk hashes behind a KV-aware-v2 router and a directory-hosting cache
server; one engine is SIGTERM'd mid-load and reborn. Asserts zero client
non-429 errors, resident-class routing actually happened, and the reborn
engine re-registered under a higher generation (its stale claims expired)
and republished.

A seventh scenario, ``run_mixed_class_overload()``
(``--scenario mixed-class-overload``), drives a mixed interactive/batch
load past fleet capacity against class-aware admission (ISSUE 20,
docs/failure-handling.md priority classes): two fakes with an interactive
reserve, one injecting ``--interactive-slo-degrade-ms`` so its interactive
TTFT p99 breaches the fleet controller's latency watermark. Asserts zero
non-429 errors, every shed landed on the batch class (the reserve kept
interactive whole), bounded interactive TTFT, at least one
``latency_protect`` decision that migrated a batch stream off the degraded
engine, and zero dropped streams (the preempted batch stream was spliced,
not cut).

A sixth scenario, ``run_fabric_outage()`` (``--scenario fabric-outage``),
exercises the peer-to-peer KV fabric (ISSUE 16, docs/kv-fabric.md): three
fabric-enabled fakes cross-pull published chains from each other; one
fabric listener is killed mid-load (``POST /fabric_down``) and the run
asserts zero client non-429 errors, real cross-engine pulls, and counted
tier fallbacks (``vllm:kv_fabric_fallbacks_total`` > 0).

Importable as ``run_chaos()`` / ``run_overload()`` /
``run_rolling_restart()`` / ``run_directory_restart()`` /
``run_fabric_outage()`` (tests/test_chaos.py wires them into tier-1) or
runnable standalone:

    python scripts/chaos_check.py --num-requests 200
    python scripts/chaos_check.py --scenario overload
    python scripts/chaos_check.py --scenario rolling-restart
    python scripts/chaos_check.py --scenario directory-restart
    python scripts/chaos_check.py --scenario fabric-outage
    python scripts/chaos_check.py --scenario mixed-class-overload
"""

from __future__ import annotations

import argparse
import collections
import json
import re
import sys
import threading

import requests

# allow running as a plain script from the repo root
sys.path.insert(0, ".")

from production_stack_tpu.testing.procs import (  # noqa: E402
    free_port,
    start_proc,
    stop_proc,
    wait_healthy,
)

CIRCUIT_RE = re.compile(r'vllm_router:circuit_state\{backend="([^"]+)"\} (\d+)')


def _router_trace_ids(base: str, limit: int = 16384) -> set:
    """Trace ids currently in the router's span ring (needs
    --enable-debug-endpoints on the router)."""
    try:
        traces = requests.get(
            f"{base}/v1/traces", params={"limit": str(limit)}, timeout=10
        ).json()
    except requests.RequestException:
        return set()
    return {t["trace_id"] for t in traces.get("traces", [])}


def _check_anomaly_dumps(
    dump_dir: str, reason: str, router_trace_ids: set
) -> dict:
    """Validate the flight-recorder anomaly dumps a chaos event should have
    produced: at least one parseable dump for ``reason`` whose window holds
    scheduler AND KV events, cross-linked to at least one PR-1 trace id the
    router also recorded. Returns a summary dict the scenarios assert on."""
    import glob
    import os

    paths = sorted(glob.glob(
        os.path.join(dump_dir, f"flightrecorder-{reason}-*.json")
    ))
    out = {
        "dump_dir": dump_dir, "reason": reason, "dumps": len(paths),
        "parseable": 0, "sched_events": 0, "kv_events": 0,
        "crosslinked_trace_ids": 0,
    }
    for path in paths:
        try:
            with open(path) as f:
                payload = json.load(f)
            events = payload["events"]
        except (OSError, ValueError, KeyError):
            continue
        out["parseable"] += 1
        out["sched_events"] += sum(1 for e in events if e["kind"] == "sched")
        out["kv_events"] += sum(1 for e in events if e["kind"] == "kv")
        dumped_ids = {
            e.get("trace_id") for e in events if e.get("trace_id")
        }
        out["crosslinked_trace_ids"] += len(dumped_ids & router_trace_ids)
    return out


def run_chaos(
    num_requests: int = 200,
    retry_budget: int = 3,
    ttft_deadline: float = 1.0,
    breaker_threshold: int = 3,
    max_tokens: int = 2,
) -> dict:
    """Run the chaos scenario; returns a summary dict (see keys below).
    Raises nothing itself — callers assert on the summary."""
    fakes, urls = [], []
    modes = [["--fail-rate", "1.0"], ["--hang"], []]
    try:
        for extra in modes:
            port = free_port()
            fakes.append(start_proc(
                ["-m", "production_stack_tpu.testing.fake_engine",
                 "--port", str(port), "--model", "fake/model",
                 "--speed", "500"] + extra
            ))
            urls.append(f"http://127.0.0.1:{port}")
        fail_url, hang_url, healthy_url = urls
        router_port = free_port()
        router = start_proc([
            "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            "--static-backends", ",".join(urls),
            "--static-models", ",".join(["fake/model"] * len(urls)),
            "--engine-stats-interval", "1",
            "--retry-max-attempts", str(retry_budget),
            "--retry-backoff-base", "0.01",
            "--deadline-ttft", str(ttft_deadline),
            "--deadline-inter-chunk", "2.0",
            "--breaker-failure-threshold", str(breaker_threshold),
            # longer than any sane run: an opened breaker must still be open
            # at the end for the assertion to be meaningful
            "--breaker-cooldown", "300",
            "--trace-buffer-size", "16384",
            "--enable-debug-endpoints",
        ])
        fakes.append(router)
        base = f"http://127.0.0.1:{router_port}"
        for proc, url in zip(fakes[:-1], urls):
            wait_healthy(f"{url}/health", proc, timeout=30)
        wait_healthy(f"{base}/health", router, timeout=30)

        sess = requests.Session()
        statuses: collections.Counter = collections.Counter()
        for _ in range(num_requests):
            r = sess.post(
                f"{base}/v1/completions",
                json={"model": "fake/model", "prompt": "x",
                      "max_tokens": max_tokens},
                timeout=60,
            )
            statuses[r.status_code] += 1

        metrics = sess.get(f"{base}/metrics", timeout=10).text
        circuit = {m.group(1): int(m.group(2))
                   for m in CIRCUIT_RE.finditer(metrics)}
        traces = sess.get(
            f"{base}/v1/traces", params={"limit": "16384"}, timeout=10
        ).json()
        attempts_per_request: collections.Counter = collections.Counter()
        for trace in traces.get("traces", []):
            for span in trace["spans"]:
                if span["name"] == "router.proxy":
                    attempts_per_request[span["attrs"].get("request_id")] += 1

        def _counter(name: str) -> float:
            m = re.search(rf"^{re.escape(name)} ([0-9.]+)$", metrics, re.M)
            return float(m.group(1)) if m else 0.0

        return {
            "statuses": dict(statuses),
            "client_5xx": sum(n for s, n in statuses.items() if s >= 500),
            "circuit_state": circuit,
            "fail_url": fail_url,
            "hang_url": hang_url,
            "healthy_url": healthy_url,
            "max_attempts_observed": max(attempts_per_request.values(), default=0),
            "traced_requests": len(attempts_per_request),
            "retry_budget": retry_budget,
            "retries_total": _counter("vllm_router:retries_total"),
            "failovers_total": _counter("vllm_router:failovers_total"),
        }
    finally:
        for p in fakes:
            stop_proc(p)


def run_overload(
    num_requests: int = 48,
    concurrency: int = 12,
    seats: int = 3,
    retry_budget: int = 3,
    max_tokens: int = 8,
) -> dict:
    """Overload scenario: arrival rate > fleet capacity.

    Two fake engines, each with bounded admission (``--saturate-after-n
    seats``), behind a shed-aware router. ``concurrency`` client threads
    drive ``num_requests`` — well past the fleet's 2 x seats in-flight
    capacity — so a slice of requests finds BOTH engines saturated and must
    come back as a clean 429 + Retry-After (never a 5xx, never a hang).
    Returns a summary dict; callers assert on it."""
    import concurrent.futures as cf
    import tempfile

    fakes, urls = [], []
    # per-engine flight-recorder dump dirs: the shed storm must trigger a
    # shed_burst anomaly dump whose window cross-links to router traces
    dump_dirs = []
    try:
        for _ in range(2):
            port = free_port()
            dump_dirs.append(tempfile.mkdtemp(prefix="pstpu-fr-overload-"))
            fakes.append(start_proc(
                ["-m", "production_stack_tpu.testing.fake_engine",
                 "--port", str(port), "--model", "fake/model",
                 # slow enough that requests overlap and saturation is real
                 "--speed", "60",
                 "--saturate-after-n", str(seats),
                 "--retry-after", "1",
                 "--flight-dump-dir", dump_dirs[-1]]
            ))
            urls.append(f"http://127.0.0.1:{port}")
        router_port = free_port()
        router = start_proc([
            "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            "--static-backends", ",".join(urls),
            "--static-models", ",".join(["fake/model"] * len(urls)),
            "--engine-stats-interval", "1",
            "--retry-max-attempts", str(retry_budget),
            "--retry-backoff-base", "0.01",
            "--breaker-failure-threshold", "3",
            "--breaker-cooldown", "300",
            # anomaly-dump cross-link check reads the router's span ring
            "--trace-buffer-size", "65536",
            "--enable-debug-endpoints",
        ])
        fakes.append(router)
        base = f"http://127.0.0.1:{router_port}"
        for proc, url in zip(fakes[:-1], urls):
            wait_healthy(f"{url}/health", proc, timeout=30)
        wait_healthy(f"{base}/health", router, timeout=30)

        statuses: collections.Counter = collections.Counter()
        missing_retry_after = 0
        hangs = 0
        lock = threading.Lock()

        def one(_i: int) -> None:
            nonlocal missing_retry_after, hangs
            try:
                r = requests.post(
                    f"{base}/v1/completions",
                    json={"model": "fake/model", "prompt": "x",
                          "max_tokens": max_tokens},
                    timeout=30,
                )
                with lock:
                    statuses[r.status_code] += 1
                    if r.status_code == 429 and "Retry-After" not in r.headers:
                        missing_retry_after += 1
            except requests.RequestException:
                with lock:
                    hangs += 1

        with cf.ThreadPoolExecutor(max_workers=concurrency) as pool:
            list(pool.map(one, range(num_requests)))

        metrics = requests.get(f"{base}/metrics", timeout=10).text
        circuit = {m.group(1): int(m.group(2))
                   for m in CIRCUIT_RE.finditer(metrics)}

        def _counter(name: str) -> float:
            m = re.search(rf"^{re.escape(name)} ([0-9.]+)$", metrics, re.M)
            return float(m.group(1)) if m else 0.0

        peaks = {}
        for url in urls:
            text = requests.get(f"{url}/metrics", timeout=10).text
            m = re.search(r"fake:running_peak\{[^}]*\} (\d+)", text)
            # None (metric missing) must FAIL the bounded-depth check, not
            # sail past it — a dropped metric is a broken invariant probe
            peaks[url] = int(m.group(1)) if m else None
        # shed-burst anomaly dumps: the storm must have produced at least
        # one parseable dump whose window carries scheduler + KV events
        # cross-linked (by trace id) to traces the router recorded
        router_ids = _router_trace_ids(base)
        anomaly_dumps = [
            _check_anomaly_dumps(d, "shed_burst", router_ids)
            for d in dump_dirs
        ]
        return {
            "anomaly_dumps": anomaly_dumps,
            "statuses": dict(statuses),
            "non_429_errors": sum(
                n for s, n in statuses.items() if s not in (200, 429)
            ) + hangs,
            "hangs": hangs,
            "missing_retry_after": missing_retry_after,
            "circuit_state": circuit,
            "urls": urls,
            "seats": seats,
            "running_peak": peaks,
            "sheds_total": _counter("vllm_router:sheds_total"),
            "failovers_total": _counter("vllm_router:failovers_total"),
        }
    finally:
        for p in fakes:
            stop_proc(p)


def run_mixed_class_overload(
    seats: int = 5,
    interactive_reserve: int = 3,
    batch_workers: int = 6,
    interactive_workers: int = 2,
    batch_tokens: int = 40,
    interactive_tokens: int = 4,
    speed: float = 25.0,
    load_s: float = 14.0,
    degrade_ms: float = 400.0,
    ttft_watermark_ms: float = 150.0,
    interactive_ttft_p99_bound_s: float = 5.0,
) -> dict:
    """Mixed-class overload scenario (ISSUE 20, docs/failure-handling.md
    priority classes): interactive + batch load past fleet capacity.

    Two fake engines with class-aware bounded admission
    (``--saturate-after-n`` + ``--interactive-reserve``: batch admission
    stops ``interactive_reserve`` seats early) behind the router; one
    engine additionally injects ``--interactive-slo-degrade-ms`` so its
    *recorded* interactive TTFT/ITL p99 breaches the fleet controller's
    ``interactive_ttft_watermark_ms`` and latency protection engages.
    ``batch_workers`` long SSE streams (tagged ``X-Priority: batch``)
    overload the fleet's batch share while ``interactive_workers`` short
    streams ride the reserve. The in-process fleet controller runs its
    latency_protect loop throughout (rebalance watermark parked out of
    reach so every migration is attributable to the policy under test).

    Caller-asserted: zero non-429 client errors, every engine-level shed
    landed on the batch class (interactive sheds == 0 — the reserve held),
    interactive TTFT p99 bounded, >= 1 latency_protect decision migrated a
    batch stream off the degraded engine, and zero dropped streams (the
    preempted batch stream spliced onto the peer, full token count)."""
    import time

    from production_stack_tpu.migration.controller import (
        ControllerPolicy,
        FleetController,
    )

    ports = [free_port() for _ in range(2)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    degraded_url, peer_url = urls[1], urls[0]
    fakes: dict = {}
    router = None
    stop_load = threading.Event()
    lock = threading.Lock()
    statuses: collections.Counter = collections.Counter()
    client_sheds: collections.Counter = collections.Counter()
    errors: list = []
    dropped_streams: list = []
    ttfts: dict = {"interactive": [], "batch": []}

    def start_fake(port: int, extra: list):
        proc = start_proc([
            "-m", "production_stack_tpu.testing.fake_engine",
            "--port", str(port), "--model", "fake/model",
            "--speed", str(speed),
            "--saturate-after-n", str(seats),
            "--interactive-reserve", str(interactive_reserve),
            "--retry-after", "0.5",
        ] + extra)
        # drain stdout: sustained load + a full 64 KB pipe wedges the
        # process's event loop (PR 5 lesson)
        threading.Thread(
            target=lambda: proc.stdout.read() if proc.stdout else None,
            daemon=True,
        ).start()
        return proc

    # only the controller's latency_protect policy may migrate in this
    # scenario: the rebalance watermark is parked above any reachable
    # pressure delta so every migration is attributable
    policy = ControllerPolicy(
        rebalance_high_delta=9.0, rebalance_low_delta=8.0,
        cooldown_s=1.0, max_concurrent_migrations=1, rebalance_k=1,
        saturation_queue_ref=seats,
        interactive_ttft_watermark_ms=ttft_watermark_ms,
        latency_release_ratio=0.7, latency_protect_k=1,
    )
    ctrl_box: dict = {}
    ctrl_stop = threading.Event()

    def controller_thread():
        import asyncio

        async def runner():
            ctrl = FleetController(
                engine_urls=urls, router_url=None, policy=policy,
                tick_interval_s=0.5,
            )
            ctrl_box["ctrl"] = ctrl
            try:
                while not ctrl_stop.is_set():
                    try:
                        await ctrl.tick()
                    except Exception:  # noqa: BLE001 - keep looping
                        pass
                    await asyncio.sleep(0.5)
            finally:
                await ctrl.close()

        asyncio.run(runner())

    try:
        fakes[peer_url] = start_fake(ports[0], [])
        fakes[degraded_url] = start_fake(
            ports[1], ["--interactive-slo-degrade-ms", str(degrade_ms)]
        )
        router_port = free_port()
        router = start_proc([
            "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            "--static-backends", ",".join(urls),
            "--static-models", ",".join(["fake/model"] * len(urls)),
            "--engine-stats-interval", "1",
            "--retry-max-attempts", "3",
            "--retry-backoff-base", "0.01",
            "--breaker-failure-threshold", "3",
            "--breaker-cooldown", "300",
        ])
        base = f"http://127.0.0.1:{router_port}"
        for u in urls:
            wait_healthy(f"{u}/health", fakes[u], timeout=30)
        wait_healthy(f"{base}/health", router, timeout=30)
        threading.Thread(
            target=lambda: router.stdout.read() if router.stdout else None,
            daemon=True,
        ).start()

        def stream_worker(wid: int, priority: str, max_tokens: int):
            sess = requests.Session()
            i = 0
            while not stop_load.is_set():
                i += 1
                t0 = time.monotonic()
                try:
                    r = sess.post(
                        f"{base}/v1/completions",
                        json={"model": "fake/model",
                              "prompt": f"{priority}-{wid}-{i} " + "ctx " * 16,
                              "max_tokens": max_tokens, "stream": True},
                        headers={"X-Priority": priority},
                        stream=True, timeout=60,
                    )
                    with lock:
                        statuses[r.status_code] += 1
                    if r.status_code == 200:
                        first = None
                        content = 0
                        saw_done = saw_error = False
                        for line in r.iter_lines():
                            if not line.startswith(b"data: "):
                                continue
                            if first is None:
                                first = time.monotonic() - t0
                            if b"[DONE]" in line:
                                saw_done = True
                            elif b'"error"' in line and b'"choices"' not in line:
                                saw_error = True
                            elif b'"text"' in line:
                                content += 1
                        with lock:
                            if first is not None:
                                ttfts[priority].append(first)
                            if saw_error:
                                errors.append(("sse_error", priority, wid))
                            elif not saw_done or content != max_tokens:
                                dropped_streams.append(
                                    (priority, wid, i, content, saw_done)
                                )
                    elif r.status_code == 429:
                        with lock:
                            client_sheds[priority] += 1
                        time.sleep(0.2)
                    else:
                        with lock:
                            errors.append((r.status_code, r.text[:200]))
                except requests.RequestException as e:
                    with lock:
                        errors.append(("exception", repr(e)))
                time.sleep(0.05)

        threads = [
            threading.Thread(
                target=stream_worker, args=(w, "batch", batch_tokens)
            )
            for w in range(batch_workers)
        ] + [
            threading.Thread(
                target=stream_worker,
                args=(w, "interactive", interactive_tokens),
            )
            for w in range(interactive_workers)
        ]
        for t in threads:
            t.start()
        ctrl_thread = threading.Thread(target=controller_thread, daemon=True)
        ctrl_thread.start()

        # run until latency protection demonstrably fired (plus a minimum
        # soak so the shed path is exercised), bounded by load_s
        t0 = time.time()
        while time.time() - t0 < load_s:
            time.sleep(0.5)
            ctrl = ctrl_box.get("ctrl")
            if (
                ctrl is not None
                and ctrl.decider.decisions_total.get("latency_protect", 0) >= 1
                and time.time() - t0 > 4.0
            ):
                break
        time.sleep(1.0)  # let the spliced stream(s) finish cleanly
        stop_load.set()
        for t in threads:
            t.join(timeout=60)
        ctrl_stop.set()
        ctrl_thread.join(timeout=10)

        by_class_re = re.compile(
            r'^(fake:(?:served|shed)_by_class_total)\{[^}]*'
            r'priority="([a-z]+)"[^}]*\} ([0-9.]+)$', re.M,
        )
        served_by_class: collections.Counter = collections.Counter()
        shed_by_class: collections.Counter = collections.Counter()
        gauges: dict = {}
        for u in urls:
            text = requests.get(f"{u}/metrics", timeout=10).text
            for m in by_class_re.finditer(text):
                tgt = (
                    served_by_class if "served" in m.group(1)
                    else shed_by_class
                )
                tgt[m.group(2)] += float(m.group(3))
            vals = {}
            for m in re.finditer(
                r"^((?:vllm|fake):[a-z0-9_]+)(?:\{[^}]*\})? "
                r"([0-9.eE+-]+)$", text, re.M,
            ):
                vals[m.group(1)] = vals.get(m.group(1), 0.0) + float(
                    m.group(2)
                )
            gauges[u] = vals
        router_text = requests.get(f"{base}/metrics", timeout=10).text

        def _router_counter(name: str) -> float:
            m = re.search(
                rf"^{re.escape(name)} ([0-9.]+)$", router_text, re.M
            )
            return float(m.group(1)) if m else 0.0

        router_by_class = {
            m.group(1): float(m.group(2))
            for m in re.finditer(
                r'^vllm_router:requests_by_class_total\{priority="([a-z]+)"\}'
                r" ([0-9.]+)$", router_text, re.M,
            )
        }
        i_t = sorted(ttfts["interactive"])
        i_p99 = (
            i_t[min(len(i_t) - 1, int(len(i_t) * 0.99))] if i_t else None
        )
        ctrl = ctrl_box.get("ctrl")
        decisions = dict(ctrl.decider.decisions_total) if ctrl else {}
        return {
            "statuses": dict(statuses),
            "non_429_errors": len(errors),
            "errors": errors[:10],
            "dropped_streams": len(dropped_streams),
            "dropped_examples": dropped_streams[:5],
            "interactive_ttft_p99_s": i_p99,
            "interactive_ttft_p99_bound_s": interactive_ttft_p99_bound_s,
            "interactive_streams_ok": len(ttfts["interactive"]),
            "batch_streams_ok": len(ttfts["batch"]),
            "served_by_class": dict(served_by_class),
            "shed_by_class": dict(shed_by_class),
            "client_sheds_by_class": dict(client_sheds),
            "router_requests_by_class": router_by_class,
            "degraded_url": degraded_url,
            "degraded_interactive_ttft_p99_ms": gauges.get(
                degraded_url, {}
            ).get("vllm:interactive_ttft_p99_ms", 0.0),
            "latency_protect_decisions": decisions.get("latency_protect", 0),
            "controller_decisions": decisions,
            "degraded_migrations_out": gauges.get(degraded_url, {}).get(
                "fake:migrations_out_total", 0.0
            ),
            "peer_migrations_in": gauges.get(peer_url, {}).get(
                "fake:migrations_in_total", 0.0
            ),
            "session_repins_total": _router_counter(
                "vllm_router:session_repins_total"
            ),
            "splice_failures_total": _router_counter(
                "vllm_router:migration_splice_failures_total"
            ),
            "seats": seats,
            "interactive_reserve": interactive_reserve,
        }
    finally:
        stop_load.set()
        ctrl_stop.set()
        for p_ in fakes.values():
            stop_proc(p_)
        if router is not None:
            stop_proc(router)


def run_rolling_restart(
    engines: int = 3,
    workers: int = 6,
    breaker_cooldown: float = 1.5,
    return_window: float = 8.0,
    restore_pages: int = 32,
    max_tokens: int = 4,
) -> dict:
    """Rolling-restart scenario: restart every engine one at a time under
    sustained load. Returns a summary dict; callers assert on it.

    The reborn processes advertise ``--restart-restore-pages`` so the run
    also checks the warm-start metric surface a real ``--warm-start`` engine
    exports after restoring its manifest."""
    import signal as signal_mod
    import tempfile
    import time

    # one dump dir per engine SLOT, shared across its incarnations: the
    # SIGTERM drain of each dying process must leave a parseable anomaly
    # dump behind (timestamped filenames keep incarnations apart)
    dump_dirs = [
        tempfile.mkdtemp(prefix="pstpu-fr-restart-") for _ in range(engines)
    ]

    def start_fake(port: int, extra: list, dump_dir: str = "") -> "object":
        return start_proc(
            ["-m", "production_stack_tpu.testing.fake_engine",
             "--port", str(port), "--model", "fake/model",
             "--speed", "200"]
            + (["--flight-dump-dir", dump_dir] if dump_dir else [])
            + extra
        )

    ports = [free_port() for _ in range(engines)]
    fakes = [start_fake(p, [], d) for p, d in zip(ports, dump_dirs)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    router = None
    stop_load = threading.Event()
    statuses: collections.Counter = collections.Counter()
    errors: list = []
    lock = threading.Lock()
    try:
        router_port = free_port()
        router = start_proc([
            "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            "--static-backends", ",".join(urls),
            "--static-models", ",".join(["fake/model"] * len(urls)),
            "--engine-stats-interval", "1",
            "--retry-max-attempts", "3",
            "--retry-backoff-base", "0.01",
            "--breaker-failure-threshold", "2",
            "--breaker-cooldown", str(breaker_cooldown),
            # the active health loop fast-tracks an open breaker to
            # half-open the moment the reborn pod answers /health — the
            # path a K8s rotation takes (readiness gates + probes)
            "--static-backend-health-checks",
            "--health-check-interval", "0.25",
            # anomaly-dump cross-link check reads the router's span ring
            # (sized for the whole sustained-load run)
            "--trace-buffer-size", "65536",
            "--enable-debug-endpoints",
        ])
        base = f"http://127.0.0.1:{router_port}"
        for proc, url in zip(fakes, urls):
            wait_healthy(f"{url}/health", proc, timeout=30)
        wait_healthy(f"{base}/health", router, timeout=30)
        # drain the router's stdout for the whole run: it logs one routing
        # line per request, and minutes of sustained load overflow the 64 KB
        # subprocess pipe — a full pipe blocks the logging handler and
        # WEDGES the router's event loop (a harness artifact, not a router
        # bug; production stdout goes to the container runtime, which reads)
        threading.Thread(
            target=lambda: router.stdout.read() if router.stdout else None,
            daemon=True,
        ).start()

        def load_worker():
            sess = requests.Session()
            while not stop_load.is_set():
                try:
                    r = sess.post(
                        f"{base}/v1/completions",
                        json={"model": "fake/model", "prompt": "x",
                              "max_tokens": max_tokens},
                        timeout=30,
                    )
                    with lock:
                        statuses[r.status_code] += 1
                        if r.status_code not in (200, 429):
                            errors.append((r.status_code, r.text[:200]))
                except requests.RequestException as e:
                    with lock:
                        errors.append(("exception", repr(e)))
                time.sleep(0.02)  # sustained, not saturating: ~300 req/s

        threads = [threading.Thread(target=load_worker) for _ in range(workers)]
        for t in threads:
            t.start()
        time.sleep(1.0)  # steady-state traffic before the first restart

        def served_total(url: str) -> int:
            try:
                text = requests.get(f"{url}/metrics", timeout=5).text
            except requests.RequestException:
                return -1
            m = re.search(r"fake:served_total\{[^}]*\} (\d+)", text)
            return int(m.group(1)) if m else -1

        restarts = []
        for i, port in enumerate(ports):
            # graceful half of the rotation: SIGTERM -> drain -> exit
            fakes[i].send_signal(signal_mod.SIGTERM)
            rc = fakes[i].wait(timeout=20)
            # rebirth on the SAME address, warm (modelled manifest restore)
            fakes[i] = start_fake(
                port, ["--restart-restore-pages", str(restore_pages)],
                dump_dirs[i],
            )
            wait_healthy(f"{urls[i]}/health", fakes[i], timeout=30)
            # traffic must RETURN to the reborn backend within the breaker
            # half-open window: its per-process served counter climbs from 0
            t0 = time.time()
            returned_at = None
            while time.time() - t0 < return_window:
                if served_total(urls[i]) > 0:
                    returned_at = time.time() - t0
                    break
                time.sleep(0.1)
            warm = requests.get(f"{urls[i]}/metrics", timeout=5).text
            m = re.search(
                r"vllm:warm_start_restored_pages\{[^}]*\} (\d+)", warm
            )
            restarts.append({
                "url": urls[i],
                "exit_rc": rc,
                "traffic_returned_s": returned_at,
                "warm_restored_pages": int(m.group(1)) if m else 0,
            })
            time.sleep(0.5)  # settle before rotating the next engine

        stop_load.set()
        for t in threads:
            t.join(timeout=30)

        metrics = requests.get(f"{base}/metrics", timeout=10).text
        circuit = {m.group(1): int(m.group(2))
                   for m in CIRCUIT_RE.finditer(metrics)}
        # SIGTERM anomaly dumps: each rotated engine's drain must have left
        # a parseable flight-recorder dump carrying the pre-restart
        # scheduler + KV window, cross-linked to router-recorded trace ids
        router_ids = _router_trace_ids(base)
        anomaly_dumps = [
            _check_anomaly_dumps(d, "sigterm_drain", router_ids)
            for d in dump_dirs
        ]
        return {
            "anomaly_dumps": anomaly_dumps,
            "statuses": dict(statuses),
            "non_429_errors": len(errors),
            "errors": errors[:10],
            "restarts": restarts,
            "return_window": return_window,
            "restore_pages": restore_pages,
            "circuit_state": circuit,
            "urls": urls,
        }
    finally:
        stop_load.set()
        for p in fakes:
            stop_proc(p)
        if router is not None:
            stop_proc(router)


def run_directory_restart(
    engines: int = 3,
    workers: int = 4,
    prefixes: int = 4,
    settle_s: float = 2.5,
    republish_window: float = 10.0,
    directory_engine_timeout: float = 3.0,
    max_tokens: int = 4,
) -> dict:
    """Fleet-wide KV directory restart scenario (ISSUE 9).

    A cache server hosting the directory, three fake engines publishing
    deterministic per-prompt chunk hashes (``--kv-directory-url``), and a
    router in KV-aware v2 mode. Sustained load over a handful of long
    shared session prefixes concentrates each prefix on its publishing
    engine (resident routing); then one engine is SIGTERM'd mid-load and
    reborn on the same address. Asserted by the caller:

    - zero client non-429 errors across the whole rotation (the dead
      backend's resident claims must not poison routing — failover +
      directory TTL/generation fencing cover the gap),
    - the router actually routed by directory class (resident routes > 0),
    - the reborn engine re-registered under a HIGHER generation and
      republished (its stale claims were expired, not trusted).
    """
    import time

    import signal as signal_mod

    from production_stack_tpu.kvoffload.protocol import BlockingClient

    cache_port = free_port()
    cache = start_proc([
        "-m", "production_stack_tpu.kvoffload.cache_server",
        "--port", str(cache_port), "--host", "127.0.0.1",
        "--directory",
        "--directory-engine-timeout", str(directory_engine_timeout),
    ])
    dir_url = f"127.0.0.1:{cache_port}"

    def dir_dump() -> dict:
        client = BlockingClient("127.0.0.1", cache_port, timeout=5)
        try:
            hdr, _ = client.request({"op": "dir_dump"})
            return hdr
        finally:
            client.close()

    ports = [free_port() for _ in range(engines)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]

    def start_fake(port: int) -> "object":
        return start_proc([
            "-m", "production_stack_tpu.testing.fake_engine",
            "--port", str(port), "--model", "fake/model", "--speed", "300",
            "--kv-directory-url", dir_url,
        ])

    fakes = [start_fake(p) for p in ports]
    router = None
    stop_load = threading.Event()
    statuses: collections.Counter = collections.Counter()
    errors: list = []
    lock = threading.Lock()
    try:
        router_port = free_port()
        router = start_proc([
            "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            "--static-backends", ",".join(urls),
            "--static-models", ",".join(["fake/model"] * len(urls)),
            "--routing-logic", "kvaware",
            "--kv-directory-url", dir_url,
            "--engine-stats-interval", "1",
            "--retry-max-attempts", "3",
            "--retry-backoff-base", "0.01",
            "--breaker-failure-threshold", "2",
            "--breaker-cooldown", "1.0",
            # deliberately NO aggressive active health checks: the dead
            # backend is handled by retry/failover + its breaker + the
            # directory's TTL/fencing — and on a loaded CI host sub-second
            # health probes can time out against HEALTHY backends and pull
            # the whole fleet from rotation (client-visible 503s that have
            # nothing to do with the scenario under test)
        ])
        base = f"http://127.0.0.1:{router_port}"
        for proc, url in zip(fakes, urls):
            wait_healthy(f"{url}/health", proc, timeout=30)
        wait_healthy(f"{base}/health", router, timeout=30)
        # drain router stdout: sustained load logs one line per request and a
        # full 64 KB pipe wedges the event loop (PR 5 lesson)
        threading.Thread(
            target=lambda: router.stdout.read() if router.stdout else None,
            daemon=True,
        ).start()

        # long shared session prefixes (several 16-char chunks each) so the
        # fakes' published chains give the router real resident signal
        prompts = [
            f"session-{i:02d}-" + (chr(ord("a") + i) * 150) for i in range(prefixes)
        ]

        def load_worker(wid: int):
            sess = requests.Session()
            i = 0
            while not stop_load.is_set():
                i += 1
                prompt = prompts[(wid + i) % len(prompts)] + f"::{wid}-{i}"
                try:
                    r = sess.post(
                        f"{base}/v1/completions",
                        json={"model": "fake/model", "prompt": prompt,
                              "max_tokens": max_tokens},
                        timeout=30,
                    )
                    with lock:
                        statuses[r.status_code] += 1
                        if r.status_code not in (200, 429):
                            errors.append((r.status_code, r.text[:200]))
                except requests.RequestException as e:
                    with lock:
                        errors.append(("exception", repr(e)))
                time.sleep(0.03)

        threads = [
            threading.Thread(target=load_worker, args=(w,))
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        time.sleep(settle_s)  # publishes + resident routing reach steady state

        victim = urls[0]
        pre = dir_dump()
        pre_gen = (pre.get("engines", {}).get(victim) or {}).get("generation", 0)
        # SIGTERM the publishing engine mid-load; rebirth on the same address
        fakes[0].send_signal(signal_mod.SIGTERM)
        rc = fakes[0].wait(timeout=20)
        fakes[0] = start_fake(ports[0])
        wait_healthy(f"{urls[0]}/health", fakes[0], timeout=30)
        # the reborn process must re-register under a HIGHER generation and
        # republish entries as it serves (its pre-restart claims expire).
        # While it was down the surviving engines took over the existing
        # session prefixes (their resident claims now win), so feed the
        # rotation NEW cold sessions — QPS routing sends those to the
        # least-loaded backend, which is exactly how a reborn engine earns
        # traffic (and directory entries) back in production
        t0 = time.time()
        reborn_gen, republished = 0, 0
        k = 0
        while time.time() - t0 < republish_window:
            k += 1
            prompts.append(f"post-restart-{k:02d}-" + ("z" * 150))
            d = dir_dump().get("engines", {}).get(victim) or {}
            reborn_gen = d.get("generation", 0)
            republished = d.get("resident_chunks", 0)
            if reborn_gen > pre_gen and republished > 0:
                break
            time.sleep(0.25)
        time.sleep(0.5)
        stop_load.set()
        for t in threads:
            t.join(timeout=30)

        metrics = requests.get(f"{base}/metrics", timeout=10).text

        def _counter(name: str) -> float:
            m = re.search(rf"^{re.escape(name)} ([0-9.]+)$", metrics, re.M)
            return float(m.group(1)) if m else 0.0

        final = dir_dump()
        return {
            "statuses": dict(statuses),
            "non_429_errors": len(errors),
            "errors": errors[:10],
            "victim": victim,
            "victim_exit_rc": rc,
            "pre_generation": pre_gen,
            "reborn_generation": reborn_gen,
            "republished_chunks": republished,
            "expired_entries_total": final.get(
                "kv_directory_expired_entries_total", 0
            ),
            "stale_hits_total": final.get("kv_directory_stale_hits_total", 0),
            "resident_routes": _counter(
                "vllm_router:kvaware_v2_resident_routes_total"
            ),
            "restorable_routes": _counter(
                "vllm_router:kvaware_v2_restorable_routes_total"
            ),
            "cold_routes": _counter("vllm_router:kvaware_v2_cold_routes_total"),
        }
    finally:
        stop_load.set()
        for p_ in fakes:
            stop_proc(p_)
        if router is not None:
            stop_proc(router)
        stop_proc(cache)


def run_fabric_outage(
    engines: int = 3,
    workers: int = 4,
    prefixes: int = 4,
    settle_s: float = 3.0,
    outage_window: float = 12.0,
    max_tokens: int = 4,
) -> dict:
    """KV fabric outage scenario (ISSUE 16, docs/kv-fabric.md).

    Three fake engines with the peer-to-peer KV fabric enabled
    (``--fabric --kv-directory-url``) behind a round-robin router: shared
    session prefixes rotate across engines, so each engine's first request
    for a prefix PULLS the published chain from the owning peer's fabric
    listener (generation-fenced, real wire frames). Mid-load the victim's
    fabric listener is killed via ``POST /fabric_down`` — its HTTP plane
    keeps serving — while NEW prefixes keep entering the rotation. Asserted
    by the caller:

    - zero client non-429 errors for the whole run (a fabric outage is
      invisible to clients — pulls degrade to the tier path),
    - cross-engine fabric pulls actually happened
      (``vllm:kv_fabric_pulled_pages_total`` > 0 fleet-wide),
    - the outage produced counted tier fallbacks
      (``vllm:kv_fabric_fallbacks_total`` > 0 fleet-wide).
    """
    import time

    cache_port = free_port()
    cache = start_proc([
        "-m", "production_stack_tpu.kvoffload.cache_server",
        "--port", str(cache_port), "--host", "127.0.0.1",
        "--directory",
    ])
    dir_url = f"127.0.0.1:{cache_port}"
    ports = [free_port() for _ in range(engines)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    fakes = [
        start_proc([
            "-m", "production_stack_tpu.testing.fake_engine",
            "--port", str(p), "--model", "fake/model", "--speed", "300",
            "--kv-directory-url", dir_url, "--fabric",
        ])
        for p in ports
    ]
    router = None
    stop_load = threading.Event()
    statuses: collections.Counter = collections.Counter()
    errors: list = []
    lock = threading.Lock()

    def fab_counter(url: str, name: str) -> float:
        try:
            text = requests.get(f"{url}/metrics", timeout=10).text
        except requests.RequestException:
            return 0.0
        m = re.search(
            rf"^{re.escape(name)}\{{[^}}]*\}} ([0-9.]+)$", text, re.M
        )
        return float(m.group(1)) if m else 0.0

    def fleet_counter(name: str) -> float:
        return sum(fab_counter(u, name) for u in urls)

    try:
        router_port = free_port()
        router = start_proc([
            "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            "--static-backends", ",".join(urls),
            "--static-models", ",".join(["fake/model"] * len(urls)),
            # round-robin deliberately: every prefix visits every engine, so
            # cross-engine fabric pulls are guaranteed (kvaware would
            # concentrate each prefix on its owner and never pull)
            "--routing-logic", "roundrobin",
            "--engine-stats-interval", "1",
            "--retry-max-attempts", "3",
            "--retry-backoff-base", "0.01",
        ])
        base = f"http://127.0.0.1:{router_port}"
        for proc, url in zip(fakes, urls):
            wait_healthy(f"{url}/health", proc, timeout=30)
        wait_healthy(f"{base}/health", router, timeout=30)
        threading.Thread(
            target=lambda: router.stdout.read() if router.stdout else None,
            daemon=True,
        ).start()

        prompts = [
            f"fabric-{i:02d}-" + (chr(ord("a") + i) * 150)
            for i in range(prefixes)
        ]

        def load_worker(wid: int):
            sess = requests.Session()
            i = 0
            while not stop_load.is_set():
                i += 1
                prompt = prompts[(wid + i) % len(prompts)] + f"::{wid}-{i}"
                try:
                    r = sess.post(
                        f"{base}/v1/completions",
                        json={"model": "fake/model", "prompt": prompt,
                              "max_tokens": max_tokens},
                        timeout=30,
                    )
                    with lock:
                        statuses[r.status_code] += 1
                        if r.status_code not in (200, 429):
                            errors.append((r.status_code, r.text[:200]))
                except requests.RequestException as e:
                    with lock:
                        errors.append(("exception", repr(e)))
                time.sleep(0.03)

        threads = [
            threading.Thread(target=load_worker, args=(w,))
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        time.sleep(settle_s)  # publishes + cross-engine pulls reach steady state
        pre_pulled = fleet_counter("vllm:kv_fabric_pulled_pages_total")

        # kill the victim's fabric listener mid-load; its HTTP plane (and
        # its directory publishes) keep running — peers that try to pull
        # its freshly-published chains must fall back to the tier path
        victim = urls[0]
        requests.post(f"{victim}/fabric_down", timeout=10)
        t0 = time.time()
        fallbacks = pulled = 0.0
        k = 0
        while time.time() - t0 < outage_window:
            k += 1
            # new prefixes keep entering the rotation: round-robin lands
            # some on the victim FIRST, so its (fabric-dead) claims are the
            # ones peers try to pull
            prompts.append(f"post-outage-{k:02d}-" + ("z" * 150))
            pulled = fleet_counter("vllm:kv_fabric_pulled_pages_total")
            fallbacks = fleet_counter("vllm:kv_fabric_fallbacks_total")
            if pulled > 0 and fallbacks > 0:
                break
            time.sleep(0.25)
        stop_load.set()
        for t in threads:
            t.join(timeout=30)
        return {
            "statuses": dict(statuses),
            "non_429_errors": len(errors),
            "errors": errors[:10],
            "victim": victim,
            "pre_outage_pulled_pages": pre_pulled,
            "fabric_pulled_pages": pulled,
            "fabric_fallbacks": fallbacks,
            "fabric_served_pages": fleet_counter(
                "vllm:kv_fabric_served_pages_total"
            ),
        }
    finally:
        stop_load.set()
        for p_ in fakes:
            stop_proc(p_)
        if router is not None:
            stop_proc(router)
        stop_proc(cache)


def run_scale_cycle(
    base_engines: int = 2,
    peak_engines: int = 4,
    workers: int = 4,
    max_tokens: int = 30,
    speed: float = 25.0,
    phase_s: float = 3.0,
    return_window: float = 12.0,
    warm_prefetch: int = 8,
    drain_deadline: float = 20.0,
    ttft_p99_bound_s: float = 8.0,
    tensor_parallel: int = 1,
) -> dict:
    """Scale-cycle scenario (ISSUE 10): 2 -> 4 -> 2 engines under sustained
    streaming load, driven by the fleet controller (docs/migration.md).

    A directory-hosting cache server, ``peak_engines`` router-known
    addresses (standby-pod model: the router health-checks all four and
    only routes to live ones), and ``base_engines`` fake engines with
    ``--migration`` publishing to the directory. Under continuous streaming
    load:

    - the fleet controller runs its rebalance loop throughout;
    - scale-UP starts the remaining engines with
      ``--warm-prefetch-on-boot`` (they pull the fleet's top warm chunks
      before serving — the directory-driven warm-up);
    - scale-DOWN evacuates each victim with live migration
      (``FleetController.evacuate``) DURING its SIGTERM drain, so every
      in-flight stream moves to a survivor and the process exits clean.

    Caller-asserted: zero non-429 client errors, zero dropped streams
    (every started SSE stream reaches [DONE] with the full token count —
    spliced streams included), bounded TTFT p99, every drained engine
    evacuated everything before exit, and the scaled-up engines pulled
    fleet-warm chunks and served warm prefix hits."""
    import asyncio
    import signal as signal_mod
    import time

    from production_stack_tpu.migration.controller import (
        ControllerPolicy,
        FleetController,
    )

    cache_port = free_port()
    cache = start_proc([
        "-m", "production_stack_tpu.kvoffload.cache_server",
        "--port", str(cache_port), "--host", "127.0.0.1", "--directory",
        "--directory-engine-timeout", "5",
    ])
    dir_url = f"127.0.0.1:{cache_port}"
    ports = [free_port() for _ in range(peak_engines)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]

    def start_fake(port: int, extra: list) -> "object":
        # with tensor_parallel > 1 every fake advertises a sharded serving
        # mesh (vllm:tensor_parallel_degree, ISSUE 12): the scenario then
        # proves router scraping, migration, and warm-start all round-trip
        # against a sharded-engine fleet unchanged
        tp_args = (
            ["--tensor-parallel", str(tensor_parallel)]
            if tensor_parallel != 1 else []
        )
        proc = start_proc([
            "-m", "production_stack_tpu.testing.fake_engine",
            "--port", str(port), "--model", "fake/model",
            "--speed", str(speed), "--kv-directory-url", dir_url,
            "--migration",
        ] + tp_args + extra)
        # drain stdout: sustained load + a full 64 KB pipe wedges the
        # process's event loop (PR 5 lesson)
        threading.Thread(
            target=lambda: proc.stdout.read() if proc.stdout else None,
            daemon=True,
        ).start()
        return proc

    fakes: dict = {}
    for p_, u in zip(ports[:base_engines], urls[:base_engines]):
        fakes[u] = start_fake(p_, [])
    router = None
    stop_load = threading.Event()
    lock = threading.Lock()
    statuses: collections.Counter = collections.Counter()
    errors: list = []
    dropped_streams: list = []
    ttfts: list = []

    # shared controller (rebalance loop runs in its own thread/event loop;
    # evacuations reuse the same decider so decision counts accumulate)
    policy = ControllerPolicy(
        rebalance_high_delta=0.25, rebalance_low_delta=0.1,
        cooldown_s=1.0, max_concurrent_migrations=2, rebalance_k=1,
        saturation_queue_ref=4,
    )
    ctrl_box: dict = {}
    ctrl_stop = threading.Event()

    def controller_thread():
        async def runner():
            ctrl = FleetController(
                engine_urls=urls, router_url=None, policy=policy,
                tick_interval_s=0.5,
            )
            ctrl_box["ctrl"] = ctrl
            try:
                while not ctrl_stop.is_set():
                    try:
                        await ctrl.tick()
                    except Exception:  # noqa: BLE001 - keep looping
                        pass
                    await asyncio.sleep(0.5)
            finally:
                await ctrl.close()

        asyncio.run(runner())

    def scrape(url: str) -> dict:
        try:
            text = requests.get(f"{url}/metrics", timeout=5).text
        except requests.RequestException:
            return {}
        out = {}
        for m in re.finditer(
            r"^((?:vllm|vllm_router|fake):[a-z0-9_]+)(?:\{[^}]*\})? "
            r"([0-9.eE+-]+)$", text, re.M,
        ):
            out[m.group(1)] = out.get(m.group(1), 0.0) + float(m.group(2))
        return out

    try:
        router_port = free_port()
        router = start_proc([
            "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            # standby-pod model: the router knows every address; health
            # checks pull dead ones from rotation and admit them on boot
            "--static-backends", ",".join(urls),
            "--static-models", ",".join(["fake/model"] * len(urls)),
            "--engine-stats-interval", "1",
            "--retry-max-attempts", "4",
            "--retry-backoff-base", "0.01",
            "--breaker-failure-threshold", "3",
            "--breaker-cooldown", "1.0",
            "--static-backend-health-checks",
            "--health-check-interval", "0.3",
        ])
        base = f"http://127.0.0.1:{router_port}"
        for u in list(fakes):
            wait_healthy(f"{u}/health", fakes[u], timeout=30)
        wait_healthy(f"{base}/health", router, timeout=30)
        threading.Thread(
            target=lambda: router.stdout.read() if router.stdout else None,
            daemon=True,
        ).start()
        # the router health-checks ALL peak addresses (two are intentionally
        # dark standbys): wait until the live backends passed their first
        # probe, or the first load requests race an empty healthy set
        t0 = time.time()
        while time.time() - t0 < 20:
            try:
                r = requests.post(
                    f"{base}/v1/completions",
                    json={"model": "fake/model", "prompt": "probe",
                          "max_tokens": 1},
                    timeout=10,
                )
                if r.status_code == 200:
                    break
            except requests.RequestException:
                pass
            time.sleep(0.2)
        # shared session prefixes: publishes give the directory warm chains
        # the scaled-up engines prefetch
        prompts = [
            f"session-{i:02d}-" + (chr(ord("a") + i) * 120) for i in range(4)
        ]

        def load_worker(wid: int):
            sess = requests.Session()
            i = 0
            while not stop_load.is_set():
                i += 1
                prompt = prompts[(wid + i) % len(prompts)] + f"::{wid}-{i}"
                t0 = time.monotonic()
                try:
                    r = sess.post(
                        f"{base}/v1/completions",
                        json={"model": "fake/model", "prompt": prompt,
                              "max_tokens": max_tokens, "stream": True},
                        stream=True, timeout=60,
                    )
                    with lock:
                        statuses[r.status_code] += 1
                    if r.status_code == 200:
                        first = None
                        content = 0
                        saw_done = saw_error = False
                        for line in r.iter_lines():
                            if not line.startswith(b"data: "):
                                continue
                            if first is None:
                                first = time.monotonic() - t0
                            if b"[DONE]" in line:
                                saw_done = True
                            elif b'"error"' in line and b'"choices"' not in line:
                                saw_error = True
                            elif b'"text"' in line:
                                content += 1
                        with lock:
                            if first is not None:
                                ttfts.append(first)
                            if saw_error:
                                errors.append(("sse_error", prompt[:40]))
                            elif not saw_done or content != max_tokens:
                                dropped_streams.append(
                                    (prompt[:40], content, saw_done)
                                )
                    elif r.status_code != 429:
                        with lock:
                            errors.append((r.status_code, r.text[:200]))
                except requests.RequestException as e:
                    with lock:
                        errors.append(("exception", repr(e)))
                time.sleep(0.05)

        threads = [
            threading.Thread(target=load_worker, args=(w,))
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        ctrl_thread = threading.Thread(target=controller_thread, daemon=True)
        ctrl_thread.start()
        time.sleep(phase_s)  # phase 1: 2 engines under load

        # -- scale UP: 2 -> 4, new engines warm-prefetch before serving ----
        scale_up = []
        for p_, u in zip(
            ports[base_engines:peak_engines], urls[base_engines:peak_engines]
        ):
            fakes[u] = start_fake(
                p_, ["--warm-prefetch-on-boot", str(warm_prefetch)]
            )
        for u in urls[base_engines:peak_engines]:
            wait_healthy(f"{u}/health", fakes[u], timeout=30)
        # traffic must reach each scaled-up engine, and its first servings
        # must hit the prefetched fleet-warm set
        for u in urls[base_engines:peak_engines]:
            t0 = time.time()
            served = 0.0
            while time.time() - t0 < return_window:
                m = scrape(u)
                served = m.get("fake:served_total", 0)
                if served > 0 and m.get("fake:warm_prefix_hits_total", 0) > 0:
                    break
                time.sleep(0.2)
            m = scrape(u)
            scale_up.append({
                "url": u,
                "served": m.get("fake:served_total", 0),
                "warm_prefetch_chunks": m.get("fake:warm_prefetch_chunks", 0),
                "warm_prefix_hits": m.get("fake:warm_prefix_hits_total", 0),
                "took_s": round(time.time() - t0, 2),
            })
        time.sleep(phase_s)  # phase 2: 4 engines steady state

        # -- scale DOWN: 4 -> 2, evacuate each victim during its drain -----
        drains = []
        for u in urls[base_engines:peak_engines]:
            victim_metrics: dict = {}
            stop_scrape = threading.Event()

            def victim_scraper(vu=u, box=victim_metrics, ev=stop_scrape):
                while not ev.is_set():
                    m = scrape(vu)
                    if m:
                        box.update(m)
                    time.sleep(0.15)

            scr = threading.Thread(target=victim_scraper, daemon=True)
            scr.start()
            # SIGTERM first (drain: health 503 pulls it from routing, new
            # requests refused, in-flight streams keep running), THEN
            # evacuate the in-flight streams onto the survivors
            fakes[u].send_signal(signal_mod.SIGTERM)
            survivors = [x for x in urls if x != u and x in fakes]
            report = asyncio.run(
                _evacuate_once(
                    survivors + [u], u, policy, drain_deadline
                )
            )
            rc = fakes[u].wait(timeout=30)
            stop_scrape.set()
            scr.join(timeout=5)
            fakes.pop(u)
            report.update({
                "exit_rc": rc,
                "victim_migrations_out": victim_metrics.get(
                    "fake:migrations_out_total", 0
                ),
                "victim_last_running": victim_metrics.get(
                    "vllm:num_requests_running", -1
                ),
            })
            drains.append(report)
            time.sleep(0.5)

        time.sleep(1.0)
        stop_load.set()
        for t in threads:
            t.join(timeout=60)
        ctrl_stop.set()
        ctrl_thread.join(timeout=10)

        router_m = scrape(base)
        fleet = {u: scrape(u) for u in fakes}
        # serving-mesh advert round trip: each engine's own
        # vllm:tensor_parallel_degree, and the router's SCRAPED view of it
        # (/engines engine_stats — what the fleet controller's capacity
        # math reads)
        engine_tp = {
            u: m.get("vllm:tensor_parallel_degree", 0.0)
            for u, m in fleet.items()
        }
        router_tp: dict = {}
        try:
            eng_view = requests.get(f"{base}/engines", timeout=10).json()
            for ep in eng_view.get("engines", []):
                es = ep.get("engine_stats")
                if es is not None and ep["url"] in fakes:
                    router_tp[ep["url"]] = es.get("tensor_parallel")
        except requests.RequestException:
            pass
        # out-count = confirmed migrate_out ships: the evacuation reports'
        # moved counts (a victim's own counter can be unreadable in the
        # instant between its last stream leaving and the process exiting)
        # plus the surviving fleet's rebalance-driven outs
        migrations_out = sum(
            m.get("fake:migrations_out_total", 0) for m in fleet.values()
        ) + sum(d["moved"] for d in drains)
        migrations_in = sum(
            m.get("fake:migrations_in_total", 0) for m in fleet.values()
        )
        s_t = sorted(ttfts)
        ttft_p99 = (
            s_t[min(len(s_t) - 1, int(len(s_t) * 0.99))] if s_t else None
        )
        ctrl = ctrl_box.get("ctrl")
        return {
            "statuses": dict(statuses),
            "non_429_errors": len(errors),
            "errors": errors[:10],
            "dropped_streams": len(dropped_streams),
            "dropped_examples": dropped_streams[:5],
            "ttft_p99_s": ttft_p99,
            "ttft_p99_bound_s": ttft_p99_bound_s,
            "scale_up": scale_up,
            "drains": drains,
            "migrations_out_total": migrations_out,
            "migrations_in_total": migrations_in,
            "session_repins_total": router_m.get(
                "vllm_router:session_repins_total", 0
            ),
            "splice_failures_total": router_m.get(
                "vllm_router:migration_splice_failures_total", 0
            ),
            "controller_decisions": (
                dict(ctrl.decider.decisions_total) if ctrl else {}
            ),
            "tensor_parallel_cfg": tensor_parallel,
            "engine_advertised_tp": engine_tp,
            "router_scraped_tp": router_tp,
        }
    finally:
        stop_load.set()
        ctrl_stop.set()
        for p_ in fakes.values():
            stop_proc(p_)
        if router is not None:
            stop_proc(router)
        stop_proc(cache)


async def _evacuate_once(engine_urls, victim, policy, deadline_s):
    """One-shot evacuation helper (its own event loop; the controller is a
    pure HTTP client so a fresh instance is fine)."""
    from production_stack_tpu.migration.controller import FleetController

    ctrl = FleetController(engine_urls=engine_urls, policy=policy)
    try:
        return await ctrl.evacuate(victim, deadline_s=deadline_s)
    finally:
        await ctrl.close()


def main() -> int:
    p = argparse.ArgumentParser("chaos-check")
    p.add_argument("--scenario",
                   choices=["chaos", "overload", "rolling-restart",
                            "directory-restart", "scale-cycle",
                            "fabric-outage", "mixed-class-overload"],
                   default="chaos")
    p.add_argument("--num-requests", type=int, default=None)
    p.add_argument("--retry-budget", type=int, default=3)
    p.add_argument("--ttft-deadline", type=float, default=1.0)
    p.add_argument("--breaker-threshold", type=int, default=3)
    args = p.parse_args()
    from production_stack_tpu.router.resilience import OPEN

    if args.scenario == "scale-cycle":
        s = run_scale_cycle()
        print(json.dumps(s, indent=2))
        failures = []
        if s["non_429_errors"]:
            failures.append(
                f"{s['non_429_errors']} non-429 client errors: {s['errors']}"
            )
        if s["dropped_streams"]:
            failures.append(
                f"{s['dropped_streams']} dropped mid-flight streams: "
                f"{s['dropped_examples']}"
            )
        if s["ttft_p99_s"] is None or s["ttft_p99_s"] > s["ttft_p99_bound_s"]:
            failures.append(
                f"TTFT p99 {s['ttft_p99_s']} above bound "
                f"{s['ttft_p99_bound_s']}s"
            )
        if s["migrations_out_total"] < 1:
            failures.append("no live migration happened during the cycle")
        if s["migrations_in_total"] < sum(d["moved"] for d in s["drains"]):
            failures.append(
                f"migrations in {s['migrations_in_total']} < evacuated "
                f"{sum(d['moved'] for d in s['drains'])}"
            )
        if s["session_repins_total"] < 1:
            failures.append("router never spliced a migrated stream")
        if s["splice_failures_total"]:
            failures.append(
                f"{s['splice_failures_total']} migration splices failed"
            )
        for d in s["drains"]:
            if d["exit_rc"] != 0:
                failures.append(f"victim {d['victim']} exited rc={d['exit_rc']}")
            if d["residual_running"] or d["residual_migratable"]:
                failures.append(
                    f"victim {d['victim']} exited with work left: {d}"
                )
        for up in s["scale_up"]:
            if up["warm_prefetch_chunks"] <= 0 or up["warm_prefix_hits"] <= 0:
                failures.append(
                    f"scaled-up {up['url']} never warmed: {up}"
                )
            if up["served"] <= 0:
                failures.append(
                    f"scaled-up {up['url']} never took traffic: {up}"
                )
        if failures:
            print("SCALE-CYCLE CHECK FAILED: " + "; ".join(failures))
            return 1
        print("SCALE-CYCLE CHECK PASSED")
        return 0

    if args.scenario == "mixed-class-overload":
        s = run_mixed_class_overload()
        print(json.dumps(s, indent=2))
        failures = []
        if s["non_429_errors"]:
            failures.append(
                f"{s['non_429_errors']} non-429 client errors: {s['errors']}"
            )
        if s["dropped_streams"]:
            failures.append(
                f"{s['dropped_streams']} dropped mid-flight streams: "
                f"{s['dropped_examples']}"
            )
        if s["shed_by_class"].get("batch", 0) < 1:
            failures.append("overload never shed a batch request")
        if s["shed_by_class"].get("interactive", 0):
            failures.append(
                f"{s['shed_by_class']['interactive']} interactive sheds "
                "(the reserve did not hold)"
            )
        if (
            s["interactive_ttft_p99_s"] is None
            or s["interactive_ttft_p99_s"] > s["interactive_ttft_p99_bound_s"]
        ):
            failures.append(
                f"interactive TTFT p99 {s['interactive_ttft_p99_s']} above "
                f"bound {s['interactive_ttft_p99_bound_s']}s"
            )
        if s["latency_protect_decisions"] < 1:
            failures.append(
                "latency protection never preempted a batch stream"
            )
        if s["degraded_migrations_out"] < 1:
            failures.append(
                "no batch stream migrated off the degraded engine"
            )
        if s["splice_failures_total"]:
            failures.append(
                f"{s['splice_failures_total']} migration splices failed"
            )
        if failures:
            print("MIXED-CLASS-OVERLOAD CHECK FAILED: " + "; ".join(failures))
            return 1
        print("MIXED-CLASS-OVERLOAD CHECK PASSED")
        return 0

    if args.scenario == "fabric-outage":
        s = run_fabric_outage()
        print(json.dumps(s, indent=2))
        failures = []
        if s["non_429_errors"]:
            failures.append(
                f"{s['non_429_errors']} non-429 client errors/hangs: "
                f"{s['errors']}"
            )
        if s["fabric_pulled_pages"] <= 0:
            failures.append("no cross-engine fabric pull ever happened")
        if s["fabric_fallbacks"] <= 0:
            failures.append(
                "the fabric outage produced no counted tier fallbacks"
            )
        if failures:
            print("FABRIC-OUTAGE CHECK FAILED: " + "; ".join(failures))
            return 1
        print("FABRIC-OUTAGE CHECK PASSED")
        return 0

    if args.scenario == "directory-restart":
        s = run_directory_restart()
        print(json.dumps(s, indent=2))
        failures = []
        if s["non_429_errors"]:
            failures.append(
                f"{s['non_429_errors']} non-429 client errors/hangs: "
                f"{s['errors']}"
            )
        if s["resident_routes"] <= 0:
            failures.append("router never routed a resident directory hit")
        if s["reborn_generation"] <= s["pre_generation"]:
            failures.append(
                f"reborn engine did not advance its directory generation "
                f"({s['pre_generation']} -> {s['reborn_generation']})"
            )
        if s["republished_chunks"] <= 0:
            failures.append("reborn engine never republished directory entries")
        if s["expired_entries_total"] <= 0:
            failures.append(
                "the restart expired no directory entries (stale claims "
                "were kept)"
            )
        if failures:
            print("DIRECTORY-RESTART CHECK FAILED: " + "; ".join(failures))
            return 1
        print("DIRECTORY-RESTART CHECK PASSED")
        return 0

    if args.scenario == "rolling-restart":
        s = run_rolling_restart()
        print(json.dumps(s, indent=2))
        failures = []
        if s["non_429_errors"]:
            failures.append(
                f"{s['non_429_errors']} non-429 client errors/hangs: "
                f"{s['errors']}"
            )
        for r in s["restarts"]:
            if r["traffic_returned_s"] is None:
                failures.append(
                    f"traffic never returned to reborn {r['url']} within "
                    f"{s['return_window']}s"
                )
            if r["warm_restored_pages"] != s["restore_pages"]:
                failures.append(
                    f"{r['url']} reborn without warm-start surface "
                    f"({r['warm_restored_pages']} != {s['restore_pages']})"
                )
        for d in s["anomaly_dumps"]:
            if not (
                d["parseable"] > 0 and d["sched_events"] > 0
                and d["kv_events"] > 0 and d["crosslinked_trace_ids"] > 0
            ):
                failures.append(
                    f"missing/incomplete sigterm anomaly dump: {d}"
                )
        if failures:
            print("ROLLING-RESTART CHECK FAILED: " + "; ".join(failures))
            return 1
        print("ROLLING-RESTART CHECK PASSED")
        return 0

    if args.scenario == "overload":
        s = run_overload(
            num_requests=args.num_requests or 48,
            retry_budget=args.retry_budget,
        )
        print(json.dumps(s, indent=2))
        failures = []
        if s["non_429_errors"]:
            failures.append(
                f"{s['non_429_errors']} non-429 client errors/hangs"
            )
        if s["missing_retry_after"]:
            failures.append(
                f"{s['missing_retry_after']} 429s without Retry-After"
            )
        for url, peak in s["running_peak"].items():
            if peak is None or peak > s["seats"]:
                failures.append(
                    f"queue depth unbounded on {url}: peak {peak} > "
                    f"{s['seats']} seats"
                )
        for url in s["urls"]:
            if s["circuit_state"].get(url) == OPEN:
                failures.append(f"sheds tripped the breaker for {url}")
        if not any(
            d["parseable"] > 0 and d["sched_events"] > 0
            and d["kv_events"] > 0 and d["crosslinked_trace_ids"] > 0
            for d in s["anomaly_dumps"]
        ):
            failures.append(
                f"no complete shed-burst anomaly dump: {s['anomaly_dumps']}"
            )
        if failures:
            print("OVERLOAD CHECK FAILED: " + "; ".join(failures))
            return 1
        print("OVERLOAD CHECK PASSED")
        return 0

    s = run_chaos(
        num_requests=args.num_requests or 200,
        retry_budget=args.retry_budget,
        ttft_deadline=args.ttft_deadline,
        breaker_threshold=args.breaker_threshold,
    )
    print(json.dumps(s, indent=2))
    failures = []
    if s["client_5xx"]:
        failures.append(f"{s['client_5xx']} client-visible 5xx")
    if s["max_attempts_observed"] > s["retry_budget"]:
        failures.append(
            f"a request used {s['max_attempts_observed']} proxy attempts "
            f"(budget {s['retry_budget']})"
        )
    for label in ("fail_url", "hang_url"):
        if s["circuit_state"].get(s[label]) != OPEN:
            failures.append(f"breaker for {label}={s[label]} is not open")
    if failures:
        print("CHAOS CHECK FAILED: " + "; ".join(failures))
        return 1
    print("CHAOS CHECK PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
