#!/usr/bin/env python3
"""Chaos smoke for the router's failure-domain layer (docs/failure-handling.md).

Launches three fake engines — one ``--fail-rate 1.0`` (every request 500s),
one ``--hang`` (accepts requests, never responds), one healthy — behind a
router with retry/failover, a TTFT deadline, and passive circuit breaking
enabled, then drives a request run through the router and asserts:

- zero client-visible 5xx (every failure failed over to the healthy engine),
- no request consumed more proxy attempts than the retry budget (checked
  against the router's /v1/traces span export),
- both broken backends' circuit breakers are open by the end (checked
  against vllm_router:circuit_state on /metrics).

Importable as ``run_chaos()`` (tests/test_chaos.py wires it into tier-1) or
runnable standalone:

    python scripts/chaos_check.py --num-requests 200
"""

from __future__ import annotations

import argparse
import collections
import json
import re
import sys

import requests

# allow running as a plain script from the repo root
sys.path.insert(0, ".")

from production_stack_tpu.testing.procs import (  # noqa: E402
    free_port,
    start_proc,
    stop_proc,
    wait_healthy,
)

CIRCUIT_RE = re.compile(r'vllm_router:circuit_state\{backend="([^"]+)"\} (\d+)')


def run_chaos(
    num_requests: int = 200,
    retry_budget: int = 3,
    ttft_deadline: float = 1.0,
    breaker_threshold: int = 3,
    max_tokens: int = 2,
) -> dict:
    """Run the chaos scenario; returns a summary dict (see keys below).
    Raises nothing itself — callers assert on the summary."""
    fakes, urls = [], []
    modes = [["--fail-rate", "1.0"], ["--hang"], []]
    try:
        for extra in modes:
            port = free_port()
            fakes.append(start_proc(
                ["-m", "production_stack_tpu.testing.fake_engine",
                 "--port", str(port), "--model", "fake/model",
                 "--speed", "500"] + extra
            ))
            urls.append(f"http://127.0.0.1:{port}")
        fail_url, hang_url, healthy_url = urls
        router_port = free_port()
        router = start_proc([
            "-m", "production_stack_tpu.router.app",
            "--port", str(router_port),
            "--static-backends", ",".join(urls),
            "--static-models", ",".join(["fake/model"] * len(urls)),
            "--engine-stats-interval", "1",
            "--retry-max-attempts", str(retry_budget),
            "--retry-backoff-base", "0.01",
            "--deadline-ttft", str(ttft_deadline),
            "--deadline-inter-chunk", "2.0",
            "--breaker-failure-threshold", str(breaker_threshold),
            # longer than any sane run: an opened breaker must still be open
            # at the end for the assertion to be meaningful
            "--breaker-cooldown", "300",
            "--trace-buffer-size", "16384",
            "--enable-debug-endpoints",
        ])
        fakes.append(router)
        base = f"http://127.0.0.1:{router_port}"
        for proc, url in zip(fakes[:-1], urls):
            wait_healthy(f"{url}/health", proc, timeout=30)
        wait_healthy(f"{base}/health", router, timeout=30)

        sess = requests.Session()
        statuses: collections.Counter = collections.Counter()
        for _ in range(num_requests):
            r = sess.post(
                f"{base}/v1/completions",
                json={"model": "fake/model", "prompt": "x",
                      "max_tokens": max_tokens},
                timeout=60,
            )
            statuses[r.status_code] += 1

        metrics = sess.get(f"{base}/metrics", timeout=10).text
        circuit = {m.group(1): int(m.group(2))
                   for m in CIRCUIT_RE.finditer(metrics)}
        traces = sess.get(
            f"{base}/v1/traces", params={"limit": "16384"}, timeout=10
        ).json()
        attempts_per_request: collections.Counter = collections.Counter()
        for trace in traces.get("traces", []):
            for span in trace["spans"]:
                if span["name"] == "router.proxy":
                    attempts_per_request[span["attrs"].get("request_id")] += 1

        def _counter(name: str) -> float:
            m = re.search(rf"^{re.escape(name)} ([0-9.]+)$", metrics, re.M)
            return float(m.group(1)) if m else 0.0

        return {
            "statuses": dict(statuses),
            "client_5xx": sum(n for s, n in statuses.items() if s >= 500),
            "circuit_state": circuit,
            "fail_url": fail_url,
            "hang_url": hang_url,
            "healthy_url": healthy_url,
            "max_attempts_observed": max(attempts_per_request.values(), default=0),
            "traced_requests": len(attempts_per_request),
            "retry_budget": retry_budget,
            "retries_total": _counter("vllm_router:retries_total"),
            "failovers_total": _counter("vllm_router:failovers_total"),
        }
    finally:
        for p in fakes:
            stop_proc(p)


def main() -> int:
    p = argparse.ArgumentParser("chaos-check")
    p.add_argument("--num-requests", type=int, default=200)
    p.add_argument("--retry-budget", type=int, default=3)
    p.add_argument("--ttft-deadline", type=float, default=1.0)
    p.add_argument("--breaker-threshold", type=int, default=3)
    args = p.parse_args()
    s = run_chaos(
        num_requests=args.num_requests,
        retry_budget=args.retry_budget,
        ttft_deadline=args.ttft_deadline,
        breaker_threshold=args.breaker_threshold,
    )
    print(json.dumps(s, indent=2))
    failures = []
    if s["client_5xx"]:
        failures.append(f"{s['client_5xx']} client-visible 5xx")
    if s["max_attempts_observed"] > s["retry_budget"]:
        failures.append(
            f"a request used {s['max_attempts_observed']} proxy attempts "
            f"(budget {s['retry_budget']})"
        )
    from production_stack_tpu.router.resilience import OPEN

    for label in ("fail_url", "hang_url"):
        if s["circuit_state"].get(s[label]) != OPEN:
            failures.append(f"breaker for {label}={s[label]} is not open")
    if failures:
        print("CHAOS CHECK FAILED: " + "; ".join(failures))
        return 1
    print("CHAOS CHECK PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
