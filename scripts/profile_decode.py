"""Decode-kernel memory-pipeline microbenchmark (the page-streaming floor).

Measures, per (batch, context, page_size) bucket, what the ragged paged
attention decode kernel actually achieves against HBM:

- ``hbm_gb_s``  — achieved page-streaming bandwidth: visible KV bytes the
  step must read (sum over rows of their REAL context, k+v) / wall time.
- ``tok_s``     — kernel-level decode tokens/sec (batch rows per call).
- the same numbers for the XLA gather path (``--impl xla`` / ``both``) —
  the pre-kernel baseline that materializes a contiguous [B, S] copy.
- ``contiguous_gb_s`` — a dense-copy ceiling on the same chip, so the
  scattered numbers have an upper bound next to them (round 5 measured
  ~200 GB/s contiguous vs 14-30 GB/s page-scattered; this script is how
  that pair gets re-measured after kernel changes).

The ``mixed`` case runs one bucket twice — every row at the bucket's full
context vs. most rows short — and checks that step cost scales with the
batch's real ``kv_lens``, not the bucket (the v2 ragged grid's whole
point). On TPU the check is asserted (exit 1 on failure); under
``--interpret``/CPU timings are interpreter noise, so it only smoke-tests
numerics vs the XLA oracle.

Run on the serving chip before retuning ``decode_pages_per_block`` /
``decode_prefetch_pages`` (engine/config.py); docs/benchmarking.md
"Hardware ceilings" records the measured pair per round.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.ops.attention import paged_attention_decode
from production_stack_tpu.ops.pallas.paged_attention import (
    ragged_paged_attention_decode,
)
from production_stack_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".cache", "xla")
)

# llama-3.2-1b-class attention shape (the serving flagship on one chip)
NH, KH, D = 32, 8, 64


def _scattered_case(rng, B, max_pages, page_size, lens, dtype):
    """Pools + a deliberately scattered page table: pages of a row are
    strided across the pool (worst-case DMA locality, the serving steady
    state after churn), not the fresh-allocation contiguous layout."""
    P = B * max_pages + 8
    kp = jnp.asarray(rng.randn(P, page_size, KH, D), dtype)
    vp = jnp.asarray(rng.randn(P, page_size, KH, D), dtype)
    pt = (
        np.arange(B * max_pages, dtype=np.int32)
        .reshape(max_pages, B)
        .T.copy()  # row b owns pages b, B+b, 2B+b, ... (stride B)
    )
    q = jnp.asarray(rng.randn(B, NH, D), dtype)
    return q, kp, vp, jnp.asarray(pt), jnp.asarray(lens, jnp.int32)


def _quantize_pools(kp, vp):
    """int8 twin of a pool pair + per-page per-kv-head scales
    (ops/quant.py contract), for the kv_cache_dtype=int8 sweep."""
    from production_stack_tpu.ops.quant import quantize_page_host

    # pool [P, page, KH, D]: the helper's leading axis is per-entry, so it
    # yields exactly one [KH] scale row per page
    qk, sk = quantize_page_host(np.asarray(kp, np.float32))
    qv, sv = quantize_page_host(np.asarray(vp, np.float32))
    return (
        jnp.asarray(qk), jnp.asarray(qv),
        jnp.asarray(sk), jnp.asarray(sv),
    )


def _time(fn, reps):
    fn()  # compile
    np.asarray(fn())  # post-donation/relayout settle + sync
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    np.asarray(out)  # host fetch = the only reliable sync on tunneled chips
    return (time.perf_counter() - t0) / reps


def _visible_bytes(lens, page_size, dtype, quant=False):
    pages = -(-np.maximum(np.asarray(lens), 0) // page_size)
    itemsize = 1 if quant else np.dtype(dtype).itemsize
    per_page = page_size * KH * D * itemsize + (KH * 4 if quant else 0)
    return int(pages.sum()) * per_page * 2  # k + v


def bench_bucket(rng, B, ctx, page_size, dtype, reps, impl, interpret,
                 lens=None, tag=""):
    """impl: pallas | xla | pallas_int8 (the kernel streaming int8 pages +
    dequantizing in its VMEM ring — the kv_cache_dtype=int8 serving path,
    halved byte stream)."""
    max_pages = -(-ctx // page_size)
    if lens is None:
        lens = np.full((B,), ctx, np.int32)
    q, kp, vp, pt, lens_d = _scattered_case(rng, B, max_pages, page_size,
                                            lens, dtype)
    quant = impl == "pallas_int8"
    if quant:
        qk, qv, sk, sv = _quantize_pools(kp, vp)
        fn = lambda: ragged_paged_attention_decode(
            q, qk, qv, pt, lens_d, interpret=interpret,
            k_scales=sk, v_scales=sv,
        )
    elif impl == "pallas":
        fn = lambda: ragged_paged_attention_decode(
            q, kp, vp, pt, lens_d, interpret=interpret
        )
    else:
        fn = lambda: paged_attention_decode(q, kp, vp, pt, lens_d)
    dt = _time(fn, reps)
    nbytes = _visible_bytes(lens, page_size, dtype, quant)
    per_tok = 2 * KH * D * (1 if quant else np.dtype(dtype).itemsize)
    return {
        "tag": tag or f"B{B}_ctx{ctx}_page{page_size}",
        "impl": impl,
        "batch": B,
        "context": ctx,
        "page_size": page_size,
        "kv_lens": sorted(set(int(x) for x in lens)),
        "step_ms": round(dt * 1000, 3),
        "visible_kv_mb": round(nbytes / 1e6, 1),
        "hbm_gb_s": round(nbytes / dt / 1e9, 2),
        "tok_s": round(B / dt, 1),
        "kv_bytes_per_token": per_tok,
    }


def contiguous_ceiling(dtype, on_tpu):
    """Dense-copy bandwidth on the same chip: the number the scattered
    streams are measured against."""
    mb = 512 if on_tpu else 4
    n = mb * (1 << 20) // np.dtype(dtype).itemsize
    x = jnp.arange(n, dtype=jnp.int32).astype(dtype)
    f = jax.jit(lambda a: a * 1 + 1)
    np.asarray(f(x))
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        y = f(x)
    np.asarray(y[:8])
    dt = (time.perf_counter() - t0) / reps
    # read + write of the whole buffer per iteration
    return round(2 * x.nbytes / dt / 1e9, 2)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--impl", choices=["pallas", "xla", "both", "pallas_int8"],
        default="both",
        help="'both' sweeps pallas + xla + pallas_int8 (the quantized-KV "
        "kernel path: achieved GB/s, tok/s, bytes/token vs fp)",
    )
    ap.add_argument("--reps", type=int, default=0, help="0 = auto per backend")
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--contexts", default="", help="comma list, e.g. 1024,16384")
    ap.add_argument("--page-sizes", default="", help="comma list, e.g. 16,64,128")
    ap.add_argument("--interpret", action="store_true",
                    help="force interpret mode (implied off-TPU)")
    ap.add_argument("--json", default="", help="write full results here too")
    args = ap.parse_args()

    on_tpu = jax.default_backend() not in ("cpu",)
    interpret = args.interpret or not on_tpu
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    reps = args.reps or (16 if on_tpu else 2)
    B = args.batch or (16 if on_tpu else 2)
    contexts = (
        [int(c) for c in args.contexts.split(",") if c]
        or ([1024, 4096, 16384] if on_tpu else [64, 128])
    )
    page_sizes = (
        [int(p) for p in args.page_sizes.split(",") if p]
        or ([16, 64, 128] if on_tpu else [8, 16])
    )
    impls = (
        ["pallas", "pallas_int8", "xla"] if args.impl == "both"
        else [args.impl]
    )
    rng = np.random.RandomState(0)

    results = {"platform": jax.default_backend(), "interpret": interpret,
               "buckets": [], "mixed": {}}
    results["contiguous_gb_s"] = contiguous_ceiling(dtype, on_tpu)
    print(f"contiguous_copy_gb_s {results['contiguous_gb_s']}")

    for page_size in page_sizes:
        for ctx in contexts:
            for impl in impls:
                r = bench_bucket(rng, B, ctx, page_size, dtype, reps, impl,
                                 interpret)
                results["buckets"].append(r)
                print(json.dumps(r))

    # --- mixed-length case: cost must track real kv_lens, not the bucket ---
    ctx = max(contexts)
    page_size = page_sizes[-1] if len(page_sizes) == 1 else sorted(page_sizes)[1]
    short = max(page_size, ctx // 8)
    mixed_lens = np.full((B,), short, np.int32)
    mixed_lens[: max(1, B // 8)] = ctx  # a few long rows, mostly short
    full = bench_bucket(rng, B, ctx, page_size, dtype, reps, "pallas",
                        interpret, tag="mixed_full")
    mixed = bench_bucket(rng, B, ctx, page_size, dtype, reps, "pallas",
                         interpret, lens=mixed_lens, tag="mixed_ragged")
    byte_ratio = mixed["visible_kv_mb"] / max(full["visible_kv_mb"], 1e-9)
    time_ratio = mixed["step_ms"] / max(full["step_ms"], 1e-9)
    results["mixed"] = {
        "full": full, "ragged": mixed,
        "byte_ratio": round(byte_ratio, 3),
        "time_ratio": round(time_ratio, 3),
    }
    print(json.dumps(results["mixed"]))

    # numerics smoke for the ragged case (cheap everywhere, the only
    # meaningful mixed-case signal under the interpreter)
    q, kp, vp, pt, lens_d = _scattered_case(
        np.random.RandomState(1), B, -(-ctx // page_size), page_size,
        mixed_lens, dtype,
    )
    ref = paged_attention_decode(q, kp, vp, pt, lens_d)
    out = ragged_paged_attention_decode(q, kp, vp, pt, lens_d,
                                        interpret=interpret)
    tol = 3e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=tol, rtol=tol,
    )
    print("mixed_case_numerics OK")

    # quantized-path summary + numerics: int8-vs-fp kernel tok/s per bucket
    # (the retuned decode_pages_per_block defaults are recorded from this
    # evidence), plus an interpret-safe oracle check — the quantized kernel
    # must match the XLA gather over the DEQUANTIZED pools to fp rounding
    if any(b["impl"] == "pallas_int8" for b in results["buckets"]):
        by_key = {}
        for b in results["buckets"]:
            by_key.setdefault((b["batch"], b["context"], b["page_size"]), {})[
                b["impl"]
            ] = b
        speedups = {}
        for key, d in sorted(by_key.items()):
            if "pallas" in d and "pallas_int8" in d:
                tag = d["pallas"]["tag"]
                speedups[tag] = {
                    "tok_s_fp": d["pallas"]["tok_s"],
                    "tok_s_int8": d["pallas_int8"]["tok_s"],
                    "speedup": round(
                        d["pallas_int8"]["tok_s"]
                        / max(d["pallas"]["tok_s"], 1e-9), 3,
                    ),
                    "bytes_per_token_fp": d["pallas"]["kv_bytes_per_token"],
                    "bytes_per_token_int8": d["pallas_int8"][
                        "kv_bytes_per_token"
                    ],
                }
        results["int8_speedup"] = speedups
        print(json.dumps({"int8_speedup": speedups}))
        qk, qv, sk, sv = _quantize_pools(kp, vp)
        ref_q = paged_attention_decode(
            q,
            jnp.asarray(
                np.asarray(qk, np.float32)
                * np.asarray(sk)[:, None, :, None], dtype,
            ),
            jnp.asarray(
                np.asarray(qv, np.float32)
                * np.asarray(sv)[:, None, :, None], dtype,
            ),
            pt, lens_d,
        )
        out_q = ragged_paged_attention_decode(
            q, qk, qv, pt, lens_d, interpret=interpret,
            k_scales=sk, v_scales=sv,
        )
        np.testing.assert_allclose(
            np.asarray(out_q, np.float32), np.asarray(ref_q, np.float32),
            atol=tol, rtol=tol,
        )
        print("int8_dequant_numerics OK")

    ok = True
    if on_tpu and not args.interpret:
        # ragged scaling check: a mostly-short batch in a full-context
        # bucket must run much closer to its byte share than to the
        # bucket's cost. Allow generous slack over the pure byte ratio for
        # fixed per-step overhead (dispatch, warm-up, q/out traffic).
        limit = min(1.0, byte_ratio * 2 + 0.15)
        ok = time_ratio <= limit
        print(f"mixed_scaling {'OK' if ok else 'FAIL'} "
              f"time_ratio={time_ratio:.3f} byte_ratio={byte_ratio:.3f} "
              f"limit={limit:.3f}")
    else:
        print("mixed_scaling SKIPPED (interpret-mode timings are not real)")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
