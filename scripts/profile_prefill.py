"""Decompose 1k-token prefill time on the real chip.

Separates (a) per-dispatch wall incl. fetch RTT, (b) back-to-back dispatch
rate (compute-bound estimate, RTT amortized), (c) a dense-matmul-only
baseline with the same FLOP count as the model's projections, to locate the
gap between ~12.6 ms of ideal MXU time and the ~110 ms measured TTFT.
"""

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.engine.runner import ModelRunner, StepInput
from production_stack_tpu.models import llama
from production_stack_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".cache", "xla")
)

cfg = dataclasses.replace(llama.PRESETS["llama-3.2-1b"], max_model_len=32768)
page_size = 64
prefill_len = 1024
ctx_pages = 16
runner = ModelRunner(cfg, num_pages=64, page_size=page_size, seed=0)
rng = np.random.RandomState(0)

inp = StepInput(
    input_ids=rng.randint(0, cfg.vocab_size, (1, prefill_len)),
    positions=np.arange(prefill_len)[None],
    page_table=np.arange(ctx_pages)[None],
    kv_lens=np.full((1,), prefill_len),
    temperature=np.zeros(1),
    top_k=np.zeros(1, int),
    top_p=np.ones(1),
)
for _ in range(3):
    ids, _ = runner.step(inp)
    np.asarray(ids)

# (a) dispatch+fetch per step
ts = []
for _ in range(10):
    t0 = time.perf_counter()
    ids, _ = runner.step(inp)
    np.asarray(ids)
    ts.append((time.perf_counter() - t0) * 1000)
print("a_fetch_each_ms_p50", float(np.percentile(ts, 50)))

# (b) 10 back-to-back dispatches, one fetch: per-step compute estimate
t0 = time.perf_counter()
for _ in range(10):
    ids, _ = runner.step(inp)
np.asarray(ids)
tb = (time.perf_counter() - t0) * 1000
print("b_pipelined_ms_per_step", tb / 10)

# (c) dense matmul baseline, same projection FLOPs as one 1k-token forward
H, I, L, V = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers, cfg.vocab_size
NH, KH, D = cfg.num_attention_heads, cfg.num_kv_heads, cfg.head_dim
x = jnp.zeros((prefill_len, H), jnp.bfloat16)
wq = jnp.zeros((L, H, NH * D), jnp.bfloat16)
wk = jnp.zeros((L, H, KH * D), jnp.bfloat16)
wv = jnp.zeros((L, H, KH * D), jnp.bfloat16)
wo = jnp.zeros((L, NH * D, H), jnp.bfloat16)
wg = jnp.zeros((L, H, I), jnp.bfloat16)
wu = jnp.zeros((L, H, I), jnp.bfloat16)
wd = jnp.zeros((L, I, H), jnp.bfloat16)
head = jnp.zeros((H, V), jnp.bfloat16)


@jax.jit
def dense(x, wq, wk, wv, wo, wg, wu, wd, head):
    def layer(x, w):
        q, k, v, o, g, u, d = w
        a = ((x @ q) @ o.T[: q.shape[1]].T) if False else (x @ q) @ o
        x = x + a + (x @ k) @ jnp.zeros((KH * D, H), jnp.bfloat16) + (x @ v) @ jnp.zeros((KH * D, H), jnp.bfloat16)
        m = (jax.nn.silu(x @ g) * (x @ u)) @ d
        return x + m, None

    x, _ = jax.lax.scan(layer, x, (wq, wk, wv, wo, wg, wu, wd))
    return (x[-1:] @ head).astype(jnp.float32)


r = dense(x, wq, wk, wv, wo, wg, wu, wd, head)
np.asarray(r)
t0 = time.perf_counter()
for _ in range(10):
    r = dense(x, wq, wk, wv, wo, wg, wu, wd, head)
np.asarray(r)
print("c_dense_ms_per_step", (time.perf_counter() - t0) * 100)

flops = prefill_len * 2 * (
    L * (H * NH * D + 2 * H * KH * D + NH * D * H + 3 * H * I)
) + 2 * H * V
print("proj_gflops", flops / 1e9)
