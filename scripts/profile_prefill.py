"""Prefill-kernel memory-pipeline microbenchmark (mirror of
scripts/profile_decode.py for the chunked-prefill side).

Measures, per (chunk, context) bucket, what the ragged prefill attention
kernel (ops/pallas/prefill_attention.py, v2) actually achieves:

- ``hbm_gb_s``  — achieved page-streaming bandwidth: paged KV bytes the
  call's DMA ring moves (each query block sweeps the row's REAL history,
  k+v) / wall time.
- ``tok_s``     — kernel-level prefill tokens/sec (chunk tokens per call).
- the same numbers for the XLA gather+flash path (``--impl xla``/``both``)
  — the pre-kernel baseline that materializes a contiguous [B, S] copy of
  the pool and runs the online softmax as a lax.scan.
- ``fused_ms``  — the same kernel call with the fused paged-KV write on
  (the serving default): the delta over the read-only call is the
  in-kernel write cost that replaces the runner's post-scan scatter pass.
- ``contiguous_gb_s`` — a dense-copy ceiling on the same chip, so the
  scattered numbers have an upper bound next to them.

The ``mixed`` case runs one bucket twice — every row with the bucket's
full history vs. mixed 1k/16k-style histories in ONE batch — and checks
that call cost scales with the batch's REAL summed work, not the bucket
(the packed ragged grid's whole point). On TPU the check is asserted
(exit 1 on failure); under ``--interpret``/CPU timings are interpreter
noise, so it only smoke-tests numerics vs the XLA oracle (including
fused-write pool bit-identity vs the scatter path).

Run on the serving chip before retuning ``prefill_pages_per_block`` /
``prefill_prefetch_pages`` (engine/config.py); docs/benchmarking.md
"Hardware ceilings" records the measured pair per round.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.ops.attention import (
    flash_attention,
    gather_kv_pages,
    stale_kv_positions,
    write_kv_pages,
)
from production_stack_tpu.ops.pallas.prefill_attention import (
    ragged_paged_attention_prefill,
)
from production_stack_tpu.utils.compile_cache import enable_persistent_cache

enable_persistent_cache(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".cache", "xla")
)

# llama-3.2-1b-class attention shape (the serving flagship on one chip)
NH, KH, D = 32, 8, 64


def _quantize_pools(kp, vp):
    """int8 twin of a pool pair + per-page per-kv-head scales
    (ops/quant.py contract), for the kv_cache_dtype=int8 sweep."""
    from production_stack_tpu.ops.quant import quantize_page_host

    qk, sk = quantize_page_host(np.asarray(kp, np.float32))
    qv, sv = quantize_page_host(np.asarray(vp, np.float32))
    return jnp.asarray(qk), jnp.asarray(qv), jnp.asarray(sk), jnp.asarray(sv)


def _case(rng, B, T, page_size, computed, dtype):
    """Chunk of T fresh tokens per row over ``computed[b]`` paged history.
    Pages are deliberately scattered across the pool (worst-case DMA
    locality — the serving steady state after churn)."""
    max_pages = max(1, -(-int(max(computed) + T) // page_size))
    P = B * max_pages + 8
    kp = jnp.asarray(rng.randn(P, page_size, KH, D), dtype)
    vp = jnp.asarray(rng.randn(P, page_size, KH, D), dtype)
    pt = (
        np.arange(B * max_pages, dtype=np.int32)
        .reshape(max_pages, B)
        .T.copy()  # row b owns pages b, B+b, 2B+b, ... (stride B)
    )
    q = jnp.asarray(rng.randn(B, T, NH, D), dtype)
    kc = jnp.asarray(rng.randn(B, T, KH, D), dtype)
    vc = jnp.asarray(rng.randn(B, T, KH, D), dtype)
    pos = np.full((B, T), -1, np.int32)
    for b in range(B):
        pos[b] = np.arange(computed[b], computed[b] + T)
    lens = jnp.asarray(np.asarray(computed) + T, jnp.int32)
    cl = jnp.full((B,), T, jnp.int32)
    return q, kp, vp, jnp.asarray(pt), jnp.asarray(pos), lens, kc, vc, cl


def _xla_path(q, kp, vp, pt, pos, lens, kc, vc):
    kg, vg = gather_kv_pages(kp, vp, pt)
    kv_pos = stale_kv_positions(pt, pos, kp.shape[1])
    k = jnp.concatenate([kg, kc.astype(kg.dtype)], axis=1)
    v = jnp.concatenate([vg, vc.astype(vg.dtype)], axis=1)
    return flash_attention(q, k, v, q_positions=pos, kv_lens=lens,
                           kv_positions=kv_pos)


_xla_jit = jax.jit(_xla_path)


def _time(fn, reps):
    first = lambda o: o[0] if isinstance(o, tuple) else o
    fn()  # compile
    np.asarray(first(fn()))  # post-donation/relayout settle + sync
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    # host fetch = the only reliable sync on tunneled chips
    np.asarray(first(out))
    return (time.perf_counter() - t0) / reps


def _streamed_bytes(computed, T, page_size, q_block, dtype, quant=False):
    """Paged KV bytes the kernel's ring moves per call: each of the chunk's
    query blocks sweeps its row's real history once (k+v)."""
    n_qb = -(-T // q_block)
    pages = -(-np.maximum(np.asarray(computed), 0) // page_size)
    itemsize = 1 if quant else np.dtype(dtype).itemsize
    per_page = page_size * KH * D * itemsize + (KH * 4 if quant else 0)
    return int(pages.sum()) * per_page * 2 * n_qb


def bench_bucket(rng, B, T, ctx, page_size, dtype, reps, impl, interpret,
                 computed=None, tag="", q_block=128):
    if computed is None:
        computed = np.full((B,), max(ctx - T, 0), np.int64)
    q, kp, vp, pt, pos, lens, kc, vc, cl = _case(
        rng, B, T, page_size, computed, dtype
    )
    quant = impl == "pallas_int8"
    if quant:
        # quantized-KV serving path: int8 ring reads (half the bytes) and
        # the fused write quantizing the chunk in-kernel
        qk, qv, sk, sv = _quantize_pools(kp, vp)
        fn = lambda: ragged_paged_attention_prefill(
            q, qk, qv, pt, pos, lens, kc, vc, cl,
            interpret=interpret, q_block=q_block,
            k_scales=sk, v_scales=sv,
        )
        fused_fn = lambda: ragged_paged_attention_prefill(
            q, qk, qv, pt, pos, lens, kc, vc, cl,
            interpret=interpret, q_block=q_block, fused_write=True,
            k_scales=sk, v_scales=sv,
        )
    elif impl == "pallas":
        fn = lambda: ragged_paged_attention_prefill(
            q, kp, vp, pt, pos, lens, kc, vc, cl,
            interpret=interpret, q_block=q_block,
        )
        fused_fn = lambda: ragged_paged_attention_prefill(
            q, kp, vp, pt, pos, lens, kc, vc, cl,
            interpret=interpret, q_block=q_block, fused_write=True,
        )
    else:
        fn = lambda: _xla_jit(q, kp, vp, pt, pos, lens, kc, vc)
        fused_fn = None
    dt = _time(fn, reps)
    nbytes = _streamed_bytes(computed, T, page_size, q_block, dtype, quant)
    out = {
        "tag": tag or f"B{B}_chunk{T}_ctx{ctx}_page{page_size}",
        "impl": impl,
        "batch": B,
        "chunk": T,
        "context": ctx,
        "page_size": page_size,
        "histories": sorted(set(int(x) for x in computed)),
        "step_ms": round(dt * 1000, 3),
        "streamed_kv_mb": round(nbytes / 1e6, 1),
        "hbm_gb_s": round(nbytes / dt / 1e9, 2),
        "tok_s": round(B * T / dt, 1),
        "kv_bytes_per_token": 2 * KH * D
        * (1 if quant else np.dtype(dtype).itemsize),
    }
    if fused_fn is not None:
        out["fused_ms"] = round(_time(fused_fn, reps) * 1000, 3)
    return out


def contiguous_ceiling(dtype, on_tpu):
    """Dense-copy bandwidth on the same chip: the number the scattered
    streams are measured against."""
    mb = 512 if on_tpu else 4
    n = mb * (1 << 20) // np.dtype(dtype).itemsize
    x = jnp.arange(n, dtype=jnp.int32).astype(dtype)
    f = jax.jit(lambda a: a * 1 + 1)
    np.asarray(f(x))
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        y = f(x)
    np.asarray(y[:8])
    dt = (time.perf_counter() - t0) / reps
    return round(2 * x.nbytes / dt / 1e9, 2)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--impl", choices=["pallas", "xla", "both", "pallas_int8"],
        default="both",
        help="'both' sweeps pallas + xla + pallas_int8 (the quantized-KV "
        "kernel path: achieved GB/s, tok/s, bytes/token vs fp)",
    )
    ap.add_argument("--reps", type=int, default=0, help="0 = auto per backend")
    ap.add_argument("--batch", type=int, default=0)
    ap.add_argument("--chunk", type=int, default=0, help="chunk length T")
    ap.add_argument("--contexts", default="",
                    help="comma list of total contexts, e.g. 4096,16384,32768")
    ap.add_argument("--page-size", type=int, default=0)
    ap.add_argument("--interpret", action="store_true",
                    help="force interpret mode (implied off-TPU)")
    ap.add_argument("--json", default="", help="write full results here too")
    args = ap.parse_args()

    on_tpu = jax.default_backend() not in ("cpu",)
    interpret = args.interpret or not on_tpu
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    reps = args.reps or (8 if on_tpu else 2)
    B = args.batch or (1 if on_tpu else 2)
    T = args.chunk or (1024 if on_tpu else 32)
    page_size = args.page_size or (64 if on_tpu else 8)
    q_block = 128 if on_tpu else 16
    contexts = (
        [int(c) for c in args.contexts.split(",") if c]
        or ([4096, 16384, 32768] if on_tpu else [64, 128])
    )
    impls = (
        ["pallas", "pallas_int8", "xla"] if args.impl == "both"
        else [args.impl]
    )
    rng = np.random.RandomState(0)

    results = {"platform": jax.default_backend(), "interpret": interpret,
               "buckets": [], "mixed": {}}
    results["contiguous_gb_s"] = contiguous_ceiling(dtype, on_tpu)
    print(f"contiguous_copy_gb_s {results['contiguous_gb_s']}")

    for ctx in contexts:
        for impl in impls:
            r = bench_bucket(rng, max(B, 1), min(T, ctx), ctx, page_size,
                             dtype, reps, impl, interpret, q_block=q_block)
            results["buckets"].append(r)
            print(json.dumps(r))

    # --- mixed-history case: one batch, a few long histories among short
    # ones — cost must track the batch's real summed work, not the bucket
    ctx = max(contexts)
    Bm = max(B, 8 if on_tpu else 2)
    Tm = min(T, max(contexts[0] // 2, page_size * 2))
    long_hist = ctx - Tm
    short_hist = max(page_size, long_hist // 16)
    mixed = np.full((Bm,), short_hist, np.int64)
    mixed[: max(1, Bm // 8)] = long_hist
    full = bench_bucket(rng, Bm, Tm, ctx, page_size, dtype, reps, "pallas",
                        interpret, tag="mixed_full", q_block=q_block)
    rag = bench_bucket(rng, Bm, Tm, ctx, page_size, dtype, reps, "pallas",
                       interpret, computed=mixed, tag="mixed_ragged",
                       q_block=q_block)
    byte_ratio = rag["streamed_kv_mb"] / max(full["streamed_kv_mb"], 1e-9)
    time_ratio = rag["step_ms"] / max(full["step_ms"], 1e-9)
    results["mixed"] = {
        "full": full, "ragged": rag,
        "byte_ratio": round(byte_ratio, 3),
        "time_ratio": round(time_ratio, 3),
    }
    print(json.dumps(results["mixed"]))

    # numerics smoke (the only meaningful mixed-case signal under the
    # interpreter): kernel vs XLA oracle, and fused-write pool contents
    # bit-identical to the scatter path
    q, kp, vp, pt, pos, lens, kc, vc, cl = _case(
        np.random.RandomState(1), Bm, Tm, page_size, mixed, dtype
    )
    ref = _xla_jit(q, kp, vp, pt, pos, lens, kc, vc)
    out = ragged_paged_attention_prefill(
        q, kp, vp, pt, pos, lens, kc, vc, cl,
        interpret=interpret, q_block=q_block,
    )
    tol = 3e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=tol, rtol=tol,
    )
    _, kp_f, vp_f = ragged_paged_attention_prefill(
        q, kp, vp, pt, pos, lens, kc, vc, cl,
        interpret=interpret, q_block=q_block, fused_write=True,
    )
    kp_s, vp_s = write_kv_pages(kp, vp, kc.astype(kp.dtype),
                                vc.astype(vp.dtype), pt, pos)
    assert (np.asarray(kp_f) == np.asarray(kp_s)).all(), "fused k write"
    assert (np.asarray(vp_f) == np.asarray(vp_s)).all(), "fused v write"
    print("mixed_case_numerics OK (incl. fused-write pool bit-identity)")

    # quantized-path summary + numerics: int8-vs-fp kernel tok/s per bucket
    # (evidence for the retuned prefill_pages_per_block defaults), plus the
    # quantized kernel against the XLA oracle over the DEQUANTIZED pools
    if any(b["impl"] == "pallas_int8" for b in results["buckets"]):
        by_key = {}
        for b in results["buckets"]:
            by_key.setdefault((b["chunk"], b["context"]), {})[b["impl"]] = b
        speedups = {}
        for key, d in sorted(by_key.items()):
            if "pallas" in d and "pallas_int8" in d:
                speedups[d["pallas"]["tag"]] = {
                    "tok_s_fp": d["pallas"]["tok_s"],
                    "tok_s_int8": d["pallas_int8"]["tok_s"],
                    "speedup": round(
                        d["pallas_int8"]["tok_s"]
                        / max(d["pallas"]["tok_s"], 1e-9), 3,
                    ),
                    "bytes_per_token_fp": d["pallas"]["kv_bytes_per_token"],
                    "bytes_per_token_int8": d["pallas_int8"][
                        "kv_bytes_per_token"
                    ],
                }
        results["int8_speedup"] = speedups
        print(json.dumps({"int8_speedup": speedups}))
        qk, qv, sk, sv = _quantize_pools(kp, vp)
        kd = jnp.asarray(
            np.asarray(qk, np.float32)
            * np.asarray(sk)[:, None, :, None], dtype,
        )
        vd = jnp.asarray(
            np.asarray(qv, np.float32)
            * np.asarray(sv)[:, None, :, None], dtype,
        )
        ref_q = _xla_jit(q, kd, vd, pt, pos, lens, kc, vc)
        out_q = ragged_paged_attention_prefill(
            q, qk, qv, pt, pos, lens, kc, vc, cl,
            interpret=interpret, q_block=q_block,
            k_scales=sk, v_scales=sv,
        )
        np.testing.assert_allclose(
            np.asarray(out_q, np.float32), np.asarray(ref_q, np.float32),
            atol=tol, rtol=tol,
        )
        print("int8_dequant_numerics OK")

    ok = True
    if on_tpu and not args.interpret:
        # ragged scaling check: a mostly-short batch in a full-context
        # bucket must run much closer to its byte share than to the
        # bucket's cost. Prefill carries real chunk compute per row no
        # matter the history, so allow that floor plus dispatch overhead
        # over the pure byte ratio.
        limit = min(1.0, byte_ratio * 2 + 0.25)
        ok = time_ratio <= limit
        print(f"mixed_scaling {'OK' if ok else 'FAIL'} "
              f"time_ratio={time_ratio:.3f} byte_ratio={byte_ratio:.3f} "
              f"limit={limit:.3f}")
    else:
        print("mixed_scaling SKIPPED (interpret-mode timings are not real)")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=1)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
