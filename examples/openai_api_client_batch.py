#!/usr/bin/env python
"""Batch API walkthrough against the router (OpenAI Batch semantics).

Uploads a JSONL request file, starts a batch job, polls until it finishes,
and prints the per-request output file. Uses only `requests`, so it runs in
any environment the stack itself runs in; the official `openai` client works
identically against the same endpoints (set base_url to the router).

Reference analogue: examples/openai_api_client_batch.py in
FlowGPT/production-stack. Start the router with --enable-batch-api
(tutorials/04 covers the full deployment).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

import requests


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base-url", default="http://localhost:8000",
                    help="router URL (no trailing /v1)")
    ap.add_argument("--file-path", default=None,
                    help="JSONL batch input (default: batch.jsonl next to this script)")
    ap.add_argument("--endpoint", default="/v1/chat/completions")
    ap.add_argument("--poll-seconds", type=float, default=2.0)
    ap.add_argument("--timeout", type=float, default=600.0)
    args = ap.parse_args()

    path = pathlib.Path(args.file_path or pathlib.Path(__file__).parent / "batch.jsonl")
    base = args.base_url.rstrip("/")

    # 1. upload the input file (multipart, purpose=batch)
    with path.open("rb") as fh:
        r = requests.post(
            f"{base}/v1/files",
            files={"file": (path.name, fh)},
            data={"purpose": "batch"},
            timeout=30,
        )
    r.raise_for_status()
    file_meta = r.json()
    print("uploaded:", json.dumps(file_meta, indent=2))

    # 2. round-trip the metadata and content endpoints
    fid = file_meta["id"]
    print("retrieved:", requests.get(f"{base}/v1/files/{fid}", timeout=30).json())
    content = requests.get(f"{base}/v1/files/{fid}/content", timeout=30)
    print("content:", content.text.strip()[:400])

    # 3. create the batch job
    r = requests.post(
        f"{base}/v1/batches",
        json={
            "input_file_id": fid,
            "endpoint": args.endpoint,
            "completion_window": "1h",
        },
        timeout=30,
    )
    r.raise_for_status()
    batch = r.json()
    print("created batch:", json.dumps(batch, indent=2))

    print("all batches:", requests.get(f"{base}/v1/batches", timeout=30).json())

    # 4. poll to completion
    deadline = time.time() + args.timeout
    while batch["status"] in ("validating", "pending", "in_progress"):
        if time.time() > deadline:
            print("timed out waiting for batch", file=sys.stderr)
            return 1
        time.sleep(args.poll_seconds)
        batch = requests.get(f"{base}/v1/batches/{batch['id']}", timeout=30).json()
        print("status:", batch["status"])

    if batch["status"] != "completed" or not batch.get("output_file_id"):
        print("batch did not complete:", json.dumps(batch, indent=2), file=sys.stderr)
        return 1

    # 5. fetch per-request results
    out = requests.get(
        f"{base}/v1/files/{batch['output_file_id']}/content", timeout=30
    )
    out.raise_for_status()
    for line in out.text.strip().splitlines():
        rec = json.loads(line)
        print(f"--- {rec.get('custom_id')} ---")
        print(json.dumps(rec.get("response", rec), indent=2)[:600])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
