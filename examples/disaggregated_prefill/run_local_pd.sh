#!/usr/bin/env bash
# Local two-engine P/D demo (single machine; use helm kvRole values in K8s —
# tutorials/16-disagg-prefill.md). The producer engine pushes finished
# prefill KV to the consumer, and the router's disaggregated_prefill logic
# does the two-phase request flow.
set -euo pipefail

MODEL="${MODEL:-llama-debug}"
PREFILL_PORT=8101
DECODE_PORT=8102
ROUTER_PORT=8000
KV_PORT=55555

cleanup() { kill 0 2>/dev/null || true; }
trap cleanup EXIT

python -m production_stack_tpu.engine.api_server \
  --model "$MODEL" --port "$DECODE_PORT" \
  --kv-role consumer --kv-transfer-port "$KV_PORT" &

python -m production_stack_tpu.engine.api_server \
  --model "$MODEL" --port "$PREFILL_PORT" \
  --kv-role producer --kv-peer-url "http://127.0.0.1:$KV_PORT" &

for p in "$PREFILL_PORT" "$DECODE_PORT"; do
  until curl -sf "http://127.0.0.1:$p/health" >/dev/null; do sleep 1; done
done

python -m production_stack_tpu.router.app --port "$ROUTER_PORT" \
  --service-discovery static \
  --static-backends "http://127.0.0.1:$PREFILL_PORT,http://127.0.0.1:$DECODE_PORT" \
  --static-models "$MODEL,$MODEL" \
  --static-model-labels "prefill,decode" \
  --routing-logic disaggregated_prefill \
  --prefill-model-labels prefill --decode-model-labels decode &

until curl -sf "http://127.0.0.1:$ROUTER_PORT/health" >/dev/null; do sleep 1; done

curl -s "http://127.0.0.1:$ROUTER_PORT/v1/completions" \
  -H 'Content-Type: application/json' \
  -d "{\"model\": \"$MODEL\", \"prompt\": \"hello disaggregated world\", \"max_tokens\": 16}"
echo
wait
