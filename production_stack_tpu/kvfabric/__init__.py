"""Peer-to-peer KV fabric: one engine-to-engine transfer plane shared by
disaggregated prefill (streamed layer-by-layer push), directory resident-page
pulls, and live migration. See docs/kv-fabric.md."""

from production_stack_tpu.kvfabric.client import KVFabricClient
from production_stack_tpu.kvfabric.peers import (
    PeerLink,
    PeerProbeCache,
    pick_best_peer,
    transfer_cost_score,
)
from production_stack_tpu.kvfabric.server import KVFabricServer
from production_stack_tpu.kvfabric.wire import (
    FABRIC_WIRE_VERSION,
    FabricWireError,
    FrameAssembler,
    decode_frame,
    encode_frame,
    frame_to_blobs,
    verify_frame,
)

__all__ = [
    "FABRIC_WIRE_VERSION",
    "FabricWireError",
    "FrameAssembler",
    "KVFabricClient",
    "KVFabricServer",
    "PeerLink",
    "PeerProbeCache",
    "decode_frame",
    "encode_frame",
    "frame_to_blobs",
    "pick_best_peer",
    "transfer_cost_score",
    "verify_frame",
]
