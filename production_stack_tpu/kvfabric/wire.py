"""Fabric wire format: versioned, CRC-framed (pages, scales) frames.

The fabric's transfer unit is a **(pages, scales) pair** — a batch of KV
pages plus, on quantized engines, the per-page per-kv-head scales that make
the int8 bytes meaningful. fp engines ship EMPTY scales (``quant=False``),
int8 engines ship the exact pool bytes + f32 scales (ops/quant.py contract,
the same layout serde v3's ``Int8PageSerde`` persists). Carrying the scales
inside the CRC'd frame is what lifts PR 14's int8 gate on disagg and device
transfer: the raw ``DeviceKVEndpoint`` path shipped bare pool bytes, so a
quantized page would have arrived without its scales.

Frame layout (one TCP payload; the op envelope around it is the kvoffload
frame protocol, ``protocol.py``):

    u32 header_len | header JSON | body

    header := {
      "fv":     FABRIC_WIRE_VERSION,        # readers refuse newer
      "keys":   [hash_hex, ...],            # one content hash per page
      "quant":  bool,                       # int8 (pages, scales) pair?
      "dtype":  "bfloat16" | "float32" | "int8" | ...,
      "shape":  [Lw, page, KH, D],          # per-page layer-WINDOW shape
      "layers": [lo, hi],                   # window into the full page
      "nlayers": L,                         # full page layer count
      "blen":   int, "crc": crc32(body),    # serde-style integrity seal
    }
    body := concat over pages of (k | v | sk | sv)
            # k, v: [Lw, page, KH, D];  sk, sv: [Lw, KH] f32 (quant only)

``layers`` is the streamed-prefill hook: the producer pushes each layer
window as the fused prefill write commits it, so the consumer assembles
pages incrementally and the decode side starts restoring before the last
layer lands. A whole-page frame is simply ``layers == [0, L]``.

Integrity mirrors the serde contract (serde.py): readers verify length and
CRC32 before trusting any byte, a frame from a future format version is
refused rather than misparsed, and corruption converts to a transfer MISS
(quarantine + tier fallback), never to silently-wrong KV.

TP invariance: frames carry whole logical pages ([.., KH, ..] over ALL kv
heads) exactly like tier blobs — the gather/scatter to head shards happens
at the runner boundary (serde.py split_kv_heads / split_kv_heads_quant), so
a tp=4 engine's frames restore into a tp=1 or tp=2 peer bit-identically.
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

from production_stack_tpu.kvoffload.serde import (
    KVIntegrityError,
    _dtype_name,
    _dtype_of,
)

_HDR = struct.Struct("!I")

# wire format version written by this build; readers accept <= this
FABRIC_WIRE_VERSION = 1
# one frame moves at most this many pages (sender-side batching bound; a
# reader refuses bigger headers outright — cheap DoS hygiene, same spirit
# as protocol.MAX_HEADER)
MAX_FRAME_PAGES = 1024


class FabricWireError(KVIntegrityError):
    """A fabric frame failed its version / length / CRC check. The receiver
    must quarantine the frame (count it, drop it) and the sender's caller
    falls back to the tier path — corrupt frames never become KV."""


def encode_frame(
    keys: "list[str]",
    ks: "list[np.ndarray]",
    vs: "list[np.ndarray]",
    sks: "list[np.ndarray] | None" = None,
    svs: "list[np.ndarray] | None" = None,
    *,
    layers: "tuple[int, int] | None" = None,
    nlayers: "int | None" = None,
) -> bytes:
    """Encode one (pages, scales) frame. ``ks``/``vs`` are per-page
    ``[Lw, page, KH, D]`` arrays; ``sks``/``svs`` are per-page ``[Lw, KH]``
    f32 scales for quantized pools (None/empty for fp engines). ``layers``
    is the (lo, hi) layer window these arrays cover; default = whole page."""
    if not keys or len(keys) != len(ks) or len(ks) != len(vs):
        raise ValueError("keys/ks/vs must align and be non-empty")
    if len(keys) > MAX_FRAME_PAGES:
        raise ValueError(f"frame exceeds {MAX_FRAME_PAGES} pages")
    quant = bool(sks)
    if quant and (len(sks) != len(keys) or len(svs or []) != len(keys)):
        raise ValueError("quant frames need one (sk, sv) pair per page")
    k0 = np.asarray(ks[0])
    shape = list(k0.shape)
    lw = shape[0]
    lo, hi = layers if layers is not None else (0, lw)
    if hi - lo != lw:
        raise ValueError(f"layer window {lo}:{hi} does not match shape {shape}")
    parts: "list[bytes]" = []
    for i in range(len(keys)):
        k, v = np.asarray(ks[i]), np.asarray(vs[i])
        if list(k.shape) != shape or list(v.shape) != shape:
            raise ValueError("all pages in a frame must share one shape")
        parts.append(np.ascontiguousarray(k).tobytes())
        parts.append(np.ascontiguousarray(v).tobytes())
        if quant:
            sk = np.ascontiguousarray(sks[i], np.float32)
            sv = np.ascontiguousarray(svs[i], np.float32)
            if sk.shape != (lw, shape[2]) or sv.shape != (lw, shape[2]):
                raise ValueError(
                    f"scales must be [Lw, KH]=({lw}, {shape[2]}), "
                    f"got {sk.shape}/{sv.shape}"
                )
            parts.append(sk.tobytes())
            parts.append(sv.tobytes())
    body = b"".join(parts)
    hdr = {
        "fv": FABRIC_WIRE_VERSION,
        "keys": list(keys),
        "quant": quant,
        "dtype": _dtype_name(k0.dtype),
        "shape": shape,
        "layers": [int(lo), int(hi)],
        "nlayers": int(nlayers if nlayers is not None else hi),
        "blen": len(body),
        "crc": zlib.crc32(body) & 0xFFFFFFFF,
    }
    enc = json.dumps(hdr).encode()
    return _HDR.pack(len(enc)) + enc + body


def verify_frame(blob: bytes) -> dict:
    """Integrity-check a frame without decoding its pages; returns the parsed
    header. Raises :class:`FabricWireError` on a malformed header, a future
    wire version, a truncated body, or a CRC mismatch."""
    try:
        (n,) = _HDR.unpack_from(blob)
        hdr = json.loads(bytes(blob[_HDR.size : _HDR.size + n]))
        if not isinstance(hdr, dict):
            raise ValueError("header is not an object")
    except (struct.error, ValueError, UnicodeDecodeError) as e:
        raise FabricWireError(f"unreadable fabric frame header: {e}") from None
    fv = int(hdr.get("fv", 0))
    if fv < 1 or fv > FABRIC_WIRE_VERSION:
        raise FabricWireError(
            f"fabric frame v{fv} unsupported (this build reads "
            f"<= v{FABRIC_WIRE_VERSION})"
        )
    keys = hdr.get("keys")
    if not isinstance(keys, list) or not keys or len(keys) > MAX_FRAME_PAGES:
        raise FabricWireError("fabric frame has no/too many page keys")
    body = memoryview(blob)[_HDR.size + n :]
    if len(body) != int(hdr.get("blen", -1)):
        raise FabricWireError(
            f"truncated fabric frame: body {len(body)} bytes, "
            f"header says {hdr.get('blen')}"
        )
    if (zlib.crc32(body) & 0xFFFFFFFF) != int(hdr.get("crc", -1)):
        raise FabricWireError("fabric frame CRC mismatch (corrupt payload)")
    return hdr


def decode_frame(blob: bytes) -> dict:
    """Verify and decode one frame. Returns::

        {"keys": [...], "quant": bool, "layers": (lo, hi), "nlayers": L,
         "pages": [(k, v, sk, sv), ...]}   # sk/sv None on fp frames

    Raises :class:`FabricWireError` on any integrity failure (the caller
    quarantines and falls back to the tier path)."""
    hdr = verify_frame(blob)
    (n,) = _HDR.unpack_from(blob)
    body = memoryview(blob)[_HDR.size + n :]
    shape = tuple(int(x) for x in hdr["shape"])
    lw, _page, kh, _d = shape
    dt = _dtype_of(hdr["dtype"])
    quant = bool(hdr["quant"])
    pbytes = int(np.prod(shape)) * dt.itemsize
    sbytes = lw * kh * 4 if quant else 0
    stride = 2 * pbytes + 2 * sbytes
    keys = hdr["keys"]
    if len(body) != stride * len(keys):
        raise FabricWireError(
            f"fabric frame body {len(body)} bytes does not cover "
            f"{len(keys)} pages of {stride} bytes"
        )
    pages = []
    for i in range(len(keys)):
        off = i * stride
        k = np.frombuffer(body[off : off + pbytes], dt).reshape(shape)
        v = np.frombuffer(body[off + pbytes : off + 2 * pbytes], dt).reshape(shape)
        sk = sv = None
        if quant:
            so = off + 2 * pbytes
            sk = np.frombuffer(body[so : so + sbytes], np.float32).reshape(lw, kh)
            sv = np.frombuffer(
                body[so + sbytes : so + 2 * sbytes], np.float32
            ).reshape(lw, kh)
        pages.append((k, v, sk, sv))
    return {
        "keys": list(keys),
        "quant": quant,
        "layers": (int(hdr["layers"][0]), int(hdr["layers"][1])),
        "nlayers": int(hdr["nlayers"]),
        "pages": pages,
    }


def frame_to_blobs(frame: dict, serde) -> "list[tuple[str, bytes]]":
    """Convert a decoded WHOLE-page frame into ``(key, tier blob)`` pairs in
    the receiver's serde, so fabric-delivered pages flow through the exact
    store/connector/restore machinery tier blobs use (CRC on read, prefix
    chain, cross-dtype handling). Quant frames always serialize through
    ``Int8PageSerde.serialize_quant`` — the scales must survive verbatim —
    regardless of the receiver's configured serde; fp frames use the
    receiver's ``serde``. Layer-partial frames are a caller error (assemble
    with :class:`FrameAssembler` first)."""
    lo, hi = frame["layers"]
    if lo != 0 or hi != frame["nlayers"]:
        raise ValueError("frame_to_blobs needs whole-page frames")
    out = []
    if frame["quant"]:
        from production_stack_tpu.kvoffload.serde import Int8PageSerde

        qserde = Int8PageSerde()
        for key, (k, v, sk, sv) in zip(frame["keys"], frame["pages"]):
            out.append((key, qserde.serialize_quant(k, sk, v, sv)))
    else:
        for key, (k, v, _sk, _sv) in zip(frame["keys"], frame["pages"]):
            out.append((key, serde.serialize(k, v)))
    return out


class FrameAssembler:
    """Receiver-side assembly of layer-streamed pages.

    The streamed-prefill producer ships each page as consecutive layer
    windows; this collects them per key and yields a whole-page frame dict
    once every layer landed. Bounded: at most ``max_pending`` keys stage at
    once (beyond that the oldest partial is dropped — the tier path covers
    it), so a producer that dies mid-page cannot grow receiver memory."""

    def __init__(self, max_pending: int = 512):
        self.max_pending = max_pending
        # key -> {"windows": {(lo, hi): (k, v, sk, sv)}, "nlayers": L,
        #         "quant": bool}
        self._pending: "dict[str, dict]" = {}
        self.dropped_partials = 0

    def add(self, frame: dict) -> "list[tuple[str, tuple]]":
        """Feed one decoded frame; returns completed ``(key, (k, v, sk, sv))``
        whole pages (layer axis re-joined, ready for frame_to_blobs-style
        serialization)."""
        lo, hi = frame["layers"]
        done = []
        for key, page in zip(frame["keys"], frame["pages"]):
            if lo == 0 and hi == frame["nlayers"]:
                done.append((key, page))
                continue
            ent = self._pending.get(key)
            if ent is None:
                while len(self._pending) >= self.max_pending:
                    self._pending.pop(next(iter(self._pending)))
                    self.dropped_partials += 1
                ent = self._pending[key] = {
                    "windows": {}, "nlayers": frame["nlayers"],
                    "quant": frame["quant"],
                }
            ent["windows"][(lo, hi)] = page
            covered = sorted(ent["windows"])
            # complete iff the sorted windows tile [0, nlayers) exactly
            pos = 0
            for wlo, whi in covered:
                if wlo != pos:
                    break
                pos = whi
            if pos != ent["nlayers"]:
                continue
            parts = [ent["windows"][w] for w in covered]
            k = np.concatenate([p[0] for p in parts], axis=0)
            v = np.concatenate([p[1] for p in parts], axis=0)
            sk = sv = None
            if ent["quant"]:
                sk = np.concatenate([p[2] for p in parts], axis=0)
                sv = np.concatenate([p[3] for p in parts], axis=0)
            done.append((key, (k, v, sk, sv)))
            del self._pending[key]
        return done
