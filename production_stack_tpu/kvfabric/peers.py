"""Per-peer link probing and transfer-cost scoring for the KV fabric.

`engine/linkprobe.py` measures the host<->device link once at startup; the
fabric extends the same idea engine-to-engine: each peer's usable bandwidth
and RTT are MEASURED (a small ping for RTT, a timed ~1 MB echo for
bandwidth — the linkprobe pilot/bulk staging, scaled to a network hop),
cached with a TTL, and re-probed after a transfer failure instead of on a
timer. NetKV (PAPERS.md) is the design source: peer selection driven by
probed link bandwidth and queue depth beats round-robin exactly when links
are asymmetric — which is the normal state between TPU pods (ICI within a
slice vs DCN between pods).

The score every chooser uses (disagg router picking a decode target, the
fleet controller picking a migration target, the engine picking a pull
source) is :func:`transfer_cost_score` — bytes/second the peer can actually
absorb right now, i.e. probed bandwidth discounted by the peer's fabric
queue depth.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

# small echo for RTT; big enough echo that bandwidth dominates RTT on any
# link worth distinguishing (1 MB ~ a KV page at common configs)
PROBE_PILOT_BYTES = 4 << 10
PROBE_BULK_BYTES = 1 << 20
# a cached probe stays trusted this long unless a transfer failure
# invalidates it first
PROBE_TTL_S = 300.0


@dataclass
class PeerLink:
    """One probed peer link. ``bandwidth`` is bytes/second measured over the
    fabric echo; ``rtt`` is seconds for a pilot round trip."""

    addr: str
    bandwidth: float = 0.0
    rtt: float = 0.0
    probed_at: float = field(default_factory=time.monotonic)
    failures: int = 0


def probe_peer_link(
    addr: str, request_fn: Callable[[dict, bytes], "tuple[dict, bytes]"]
) -> "tuple[float, float]":
    """Measure (bandwidth_bytes_per_s, rtt_s) against one fabric peer.

    ``request_fn(header, payload) -> (header, payload)`` is a fabric
    round-trip (the client's ``fabric_probe`` op: the server echoes the
    payload back). RTT comes from the pilot; bandwidth from the bulk echo
    (both directions counted, matching linkprobe's round-trip convention).
    Raises on any transport error — the caller records the failure and
    falls back to unscored selection for this peer."""
    pilot = bytes(PROBE_PILOT_BYTES)
    t0 = time.perf_counter()
    hdr, _ = request_fn({"op": "fabric_probe", "echo": len(pilot)}, pilot)
    rtt = time.perf_counter() - t0
    if not hdr.get("ok"):
        raise ConnectionError(f"fabric probe refused by {addr}: {hdr}")
    bulk = bytes(PROBE_BULK_BYTES)
    t0 = time.perf_counter()
    hdr, echoed = request_fn({"op": "fabric_probe", "echo": len(bulk)}, bulk)
    dt = time.perf_counter() - t0
    if not hdr.get("ok") or len(echoed) != len(bulk):
        raise ConnectionError(f"fabric bulk probe failed against {addr}")
    # subtract the pilot-measured RTT so tiny payloads on high-latency links
    # don't read as slow bandwidth; floor keeps the division sane
    xfer = max(dt - rtt, 1e-6)
    return (2 * len(bulk)) / xfer, rtt


class PeerProbeCache:
    """TTL cache of :class:`PeerLink` measurements, one per peer address.

    ``get`` returns the cached link, probing (via the injected probe
    callable) when missing or expired; ``invalidate`` drops a peer after a
    transfer failure so the next touch re-probes — a peer that restarted on
    a different machine class must not keep its old score. Probe failures
    are recorded (the link keeps bandwidth 0.0 → sorts last) rather than
    raised: scoring is advisory, transfers carry their own retry/breaker."""

    def __init__(
        self,
        probe_fn: Callable[[str], "tuple[float, float]"],
        ttl_s: float = PROBE_TTL_S,
    ):
        self._probe_fn = probe_fn
        self.ttl_s = ttl_s
        self._links: "dict[str, PeerLink]" = {}
        self._lock = threading.Lock()
        self.probes = 0
        self.probe_failures = 0

    def get(self, addr: str) -> PeerLink:
        now = time.monotonic()
        with self._lock:
            link = self._links.get(addr)
            if link is not None and now - link.probed_at < self.ttl_s:
                return link
        self.probes += 1
        try:
            bw, rtt = self._probe_fn(addr)
            link = PeerLink(addr, bandwidth=bw, rtt=rtt, probed_at=now)
        except Exception as e:  # noqa: BLE001 - scoring must not break transfer
            self.probe_failures += 1
            logger.warning("fabric peer probe failed for %s: %s", addr, e)
            prev = self._links.get(addr)
            link = PeerLink(
                addr, probed_at=now,
                failures=(prev.failures + 1 if prev else 1),
            )
        with self._lock:
            self._links[addr] = link
        return link

    def invalidate(self, addr: str) -> None:
        with self._lock:
            link = self._links.pop(addr, None)
        if link is not None:
            logger.info("fabric peer %s invalidated after failure", addr)

    def snapshot(self) -> "dict[str, PeerLink]":
        with self._lock:
            return dict(self._links)


def transfer_cost_score(
    bandwidth: float, queue_depth: "float | int", rtt: float = 0.0
) -> float:
    """Higher = better target. Probed bandwidth discounted by the peer's
    in-flight fabric ops (NetKV's cost model: a fast link behind a deep
    queue is a slow link), with RTT as a mild tiebreak between idle peers."""
    depth = max(0.0, float(queue_depth))
    score = float(bandwidth) / (1.0 + depth)
    if rtt > 0:
        score /= 1.0 + min(rtt, 1.0)
    return score


def pick_best_peer(
    candidates: "list[tuple[str, float, float]]",
) -> Optional[str]:
    """``candidates`` = [(url, bandwidth, queue_depth)]; returns the url with
    the best transfer-cost score, or None for an empty list. All-zero
    bandwidths (nothing probed yet) return None so callers keep their
    round-robin default rather than a degenerate argmax."""
    if not candidates:
        return None
    if all(bw <= 0 for _, bw, _ in candidates):
        return None
    best = max(candidates, key=lambda c: transfer_cost_score(c[1], c[2]))
    return best[0]
