"""KV fabric listener: one engine's serving side of the peer-to-peer plane.

Every engine (and the fake engine) runs one ``KVFabricServer``. Peers speak
the kvoffload frame protocol (``protocol.py`` — the same envelope the cache
server and KV transfer use) with four ops:

    fabric_hello   -> {"ok", "generation", "quant", "page_size", "nlayers"}
                      peer handshake: who am I talking to, what dtype family
                      do its frames carry, which directory generation fences
                      its pages
    fabric_probe   -> echoes the payload (peers.probe_peer_link times this
                      to measure per-peer bandwidth/RTT)
    fabric_pull    -> header {keys, expect_generation?}; reply payload is
                      ONE wire frame (wire.encode_frame) holding every
                      requested page still resident here, header lists which
                      keys were found. A stale ``expect_generation`` (the
                      directory claim predates this engine's rebirth) is
                      REJECTED — generation fencing, the reborn owner must
                      not serve pages the claim's issuer never wrote
    fabric_push    -> payload is one wire frame; verified + decoded, pages
                      land through the injected sink (streamed disagg
                      prefill and migration ship through this). Corrupt
                      frames are QUARANTINED (counted, dropped, error reply)
                      — the sender's caller falls back to the tier path

The server is transport only: page bytes come from / go to injected
callables (``pages_fn``/``sink_fn``), which the engine routes through its
device thread — the listener thread never touches jax state (GC001/GC002
discipline, same split as ``KVTransferReceiver``). ``queue_depth`` counts
in-flight ops and is exported on /metrics; the router and fleet controller
fold it into transfer-cost scores (peers.transfer_cost_score).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Callable, Optional

from production_stack_tpu.kvfabric.wire import FabricWireError, decode_frame
from production_stack_tpu.kvoffload.protocol import read_frame, write_frame
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class KVFabricServer:
    """Asyncio TCP listener in its own thread (KVTransferReceiver pattern:
    the engine loop and device thread stay untouched)."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        *,
        generation: int = 0,
        quant: bool = False,
        page_size: int = 0,
        nlayers: int = 0,
        pages_fn: Optional[Callable[["list[str]"], "tuple[list[str], bytes]"]] = None,
        sink_fn: Optional[Callable[[dict], int]] = None,
        advertise_host: Optional[str] = None,
    ):
        self.host, self.port = host, port
        self.generation = int(generation)
        self.quant = bool(quant)
        self.page_size = int(page_size)
        self.nlayers = int(nlayers)
        # pages_fn(keys) -> (found_keys, frame_bytes): gather resident pages
        # and encode one wire frame (engine: device-thread get_pages[_quant]
        # + wire.encode_frame). sink_fn(decoded_frame) -> pages_stored.
        self.pages_fn = pages_fn
        self.sink_fn = sink_fn
        self._advertise_host = advertise_host
        self.queue_depth = 0
        self.served_pages = 0
        self.received_pages = 0
        self.corrupt_frames = 0
        self.stale_generation_pulls = 0
        self.errors = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.bound_port: Optional[int] = None

    @property
    def address(self) -> str:
        host = self._advertise_host or (
            "127.0.0.1" if self.host in ("0.0.0.0", "") else self.host
        )
        return f"{host}:{self.bound_port or self.port}"

    async def _handle_op(self, hdr: dict, payload: bytes) -> "tuple[dict, bytes]":
        op = hdr.get("op")
        if op == "fabric_hello":
            return {
                "ok": True,
                "generation": self.generation,
                "quant": self.quant,
                "page_size": self.page_size,
                "nlayers": self.nlayers,
            }, b""
        if op == "fabric_probe":
            return {"ok": True, "echo": len(payload)}, payload
        if op == "fabric_pull":
            expect = hdr.get("expect_generation")
            if expect is not None and int(expect) != self.generation:
                # generation fence: the claim was issued by a previous
                # incarnation of this owner; its pages are gone or reused
                self.stale_generation_pulls += 1
                return {
                    "ok": False,
                    "error": "stale_generation",
                    "generation": self.generation,
                }, b""
            keys = hdr.get("keys") or []
            if self.pages_fn is None or not keys:
                return {"ok": True, "found": []}, b""
            try:
                found, frame = await asyncio.to_thread(self.pages_fn, keys)
            except Exception as e:  # noqa: BLE001 - a pull must not kill the listener
                self.errors += 1
                logger.warning("fabric pull of %d keys failed: %s", len(keys), e)
                return {"ok": False, "error": "pull_failed"}, b""
            self.served_pages += len(found)
            return {"ok": True, "found": list(found)}, frame or b""
        if op == "fabric_push":
            if self.sink_fn is None:
                return {"ok": False, "error": "no_sink"}, b""
            try:
                frame = decode_frame(payload)
            except FabricWireError as e:
                # quarantine: a corrupt frame admitted here would scatter
                # wrong KV downstream; drop it and tell the sender, whose
                # caller falls back to the tier path
                self.corrupt_frames += 1
                logger.warning("quarantining corrupt fabric frame: %s", e)
                return {"ok": False, "error": "integrity"}, b""
            try:
                stored = int(await asyncio.to_thread(self.sink_fn, frame) or 0)
            except Exception as e:  # noqa: BLE001
                self.errors += 1
                logger.warning("fabric push sink failed: %s", e)
                return {"ok": False, "error": "sink_failed"}, b""
            self.received_pages += stored
            return {"ok": True, "stored": stored}, b""
        return {"ok": False, "error": f"bad op {op!r}"}, b""

    async def _handle(self, reader, writer):
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    hdr, payload = await read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                self.queue_depth += 1
                try:
                    rhdr, rpayload = await self._handle_op(hdr, payload)
                finally:
                    self.queue_depth -= 1
                await write_frame(writer, rhdr, rpayload)
        except Exception as e:  # noqa: BLE001
            logger.warning("fabric server: client %s error: %s", peer, e)
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    def start(self) -> None:
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def serve():
                server = await asyncio.start_server(self._handle, self.host, self.port)
                self.bound_port = server.sockets[0].getsockname()[1]
                self._started.set()
                async with server:
                    await server.serve_forever()

            try:
                self._loop.run_until_complete(serve())
            except asyncio.CancelledError:
                pass

        self._thread = threading.Thread(target=run, daemon=True, name="kv-fabric")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("KV fabric server failed to start")
        logger.info("kv fabric listening on %s", self.address)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(
                lambda: [t.cancel() for t in asyncio.all_tasks(self._loop)]
            )
        if self._thread is not None:
            self._thread.join(timeout=5)

    def stats(self) -> dict:
        return {
            "queue_depth": self.queue_depth,
            "served_pages": self.served_pages,
            "received_pages": self.received_pages,
            "corrupt_frames": self.corrupt_frames,
            "stale_generation_pulls": self.stale_generation_pulls,
            "errors": self.errors,
        }
