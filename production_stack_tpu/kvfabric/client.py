"""KV fabric client: the pushing/pulling side every mover shares.

One ``KVFabricClient`` per engine serves all three movers — streamed disagg
prefill pushes, directory resident-page pulls, and migration page-chain
ships. It owns:

- one lazily-connected :class:`BlockingClient` per peer address (guarded by
  a per-peer lock: callers run on the device thread, the puller executor,
  and the migration executor concurrently);
- a per-peer circuit breaker: ``BREAKER_THRESHOLD`` consecutive failures
  open the breaker for ``BREAKER_COOLDOWN_S`` — during the cooldown every
  fabric call against that peer fails instantly and the caller takes its
  tier fallback, so a dead peer costs one timeout, not one per page;
- bounded retries (``retries`` config) below the breaker;
- the :class:`PeerProbeCache` (peers.py) so choosers can score peers, with
  failures invalidating the cached probe;
- the fabric counters and latency histograms the engine exports on
  /metrics (``vllm:kv_fabric_*``).

Every public method degrades to a documented failure value (False/None)
instead of raising: the fabric is an OPTIMIZATION over the tier path, and
the contract is that a fabric outage converts to tier traffic + counted
fallbacks, never to request errors.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from production_stack_tpu.kvfabric import peers as fabric_peers
from production_stack_tpu.kvfabric.wire import (
    FabricWireError,
    decode_frame,
    verify_frame,
)
from production_stack_tpu.kvoffload.protocol import BlockingClient, parse_hostport
from production_stack_tpu.utils.logging import init_logger
from production_stack_tpu.utils.metrics import Histogram

logger = init_logger(__name__)

BREAKER_THRESHOLD = 3
BREAKER_COOLDOWN_S = 30.0

# fabric transfers are sub-second on healthy links; buckets stretch to the
# breaker cooldown so a timing-out peer is still visible in the histogram
FABRIC_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class KVFabricClient:
    def __init__(self, retries: int = 2, timeout: float = 30.0):
        self.retries = max(0, int(retries))
        self.timeout = timeout
        self._clients: "dict[str, BlockingClient]" = {}
        self._locks: "dict[str, threading.Lock]" = {}
        self._breaker: "dict[str, tuple[int, float]]" = {}  # addr -> (fails, open_until)
        self._meta_lock = threading.Lock()
        self.probe_cache = fabric_peers.PeerProbeCache(self._probe_addr)
        self.pushed_pages = 0
        self.pulled_pages = 0
        self.fallbacks = 0
        self.corrupt_frames = 0
        self.breaker_opens = 0
        self.push_hist = Histogram(
            "vllm:kv_fabric_stream_latency_seconds",
            FABRIC_LATENCY_BUCKETS,
            "Latency of one fabric push (streamed prefill / migration ship)",
        )
        self.pull_hist = Histogram(
            "vllm:kv_fabric_pull_latency_seconds",
            FABRIC_LATENCY_BUCKETS,
            "Latency of one fabric resident-page pull",
        )

    # -- connection + breaker plumbing ----------------------------------------

    def _lock_for(self, addr: str) -> threading.Lock:
        with self._meta_lock:
            lock = self._locks.get(addr)
            if lock is None:
                lock = self._locks[addr] = threading.Lock()
            return lock

    def _client_for(self, addr: str) -> BlockingClient:
        with self._meta_lock:
            cli = self._clients.get(addr)
            if cli is None:
                host, port = parse_hostport(addr)
                cli = self._clients[addr] = BlockingClient(
                    host, port, timeout=self.timeout
                )
            return cli

    def breaker_open(self, addr: str) -> bool:
        with self._meta_lock:
            _, until = self._breaker.get(addr, (0, 0.0))
            return until > time.monotonic()

    def _record_success(self, addr: str) -> None:
        with self._meta_lock:
            self._breaker.pop(addr, None)

    def _record_failure(self, addr: str) -> None:
        with self._meta_lock:
            fails, _ = self._breaker.get(addr, (0, 0.0))
            fails += 1
            until = 0.0
            if fails >= BREAKER_THRESHOLD:
                until = time.monotonic() + BREAKER_COOLDOWN_S
                self.breaker_opens += 1
            self._breaker[addr] = (fails, until)
        if fails >= BREAKER_THRESHOLD:
            logger.warning(
                "fabric breaker OPEN for %s after %d failures (%.0fs cooldown)",
                addr, fails, BREAKER_COOLDOWN_S,
            )
        # a failed transfer invalidates the cached probe: the peer may be
        # gone, rebooted elsewhere, or congested — re-measure on recovery
        self.probe_cache.invalidate(addr)

    def _request(self, addr: str, header: dict, payload: bytes = b"") -> "tuple[dict, bytes]":
        """One fabric round trip with bounded retries under the breaker.
        Raises ConnectionError when the breaker is open or every attempt
        failed — callers convert that to their tier fallback."""
        if self.breaker_open(addr):
            raise ConnectionError(f"fabric breaker open for {addr}")
        last: Optional[Exception] = None
        for _ in range(1 + self.retries):
            try:
                with self._lock_for(addr):
                    hdr, body = self._client_for(addr).request(header, payload)
                self._record_success(addr)
                return hdr, body
            except Exception as e:  # noqa: BLE001 - retried, then surfaced
                last = e
                self._record_failure(addr)
                if self.breaker_open(addr):
                    break
        raise ConnectionError(f"fabric request to {addr} failed: {last}")

    def _probe_addr(self, addr: str) -> "tuple[float, float]":
        return fabric_peers.probe_peer_link(
            addr, lambda hdr, payload: self._request(addr, hdr, payload)
        )

    # -- public ops ------------------------------------------------------------

    def hello(self, addr: str) -> Optional[dict]:
        """Peer handshake; returns the peer's {generation, quant, page_size,
        nlayers} or None when unreachable."""
        try:
            hdr, _ = self._request(addr, {"op": "fabric_hello"})
            return hdr if hdr.get("ok") else None
        except Exception:  # noqa: BLE001
            return None

    def probe(self, addr: str) -> fabric_peers.PeerLink:
        """Cached per-peer bandwidth/RTT (re-probed on TTL or failure)."""
        return self.probe_cache.get(addr)

    def push(self, addr: str, frame: bytes) -> bool:
        """Ship one wire frame (already encoded) to a peer's sink. Returns
        False on any failure — the caller counts a fallback and takes the
        tier path for those pages."""
        t0 = time.perf_counter()
        try:
            # pre-flight the frame locally: a frame corrupted before send
            # (encoder bug, memory fault) must not spend a network round
            # trip to be quarantined by the peer
            n_pages = len(verify_frame(frame)["keys"])
        except FabricWireError as e:
            self.corrupt_frames += 1
            logger.warning("refusing to push corrupt fabric frame: %s", e)
            return False
        try:
            hdr, _ = self._request(addr, {"op": "fabric_push"}, frame)
            if not hdr.get("ok"):
                if hdr.get("error") == "integrity":
                    self.corrupt_frames += 1
                return False
            self.pushed_pages += n_pages
            self.push_hist.observe(time.perf_counter() - t0)
            return True
        except Exception as e:  # noqa: BLE001
            logger.debug("fabric push to %s failed: %s", addr, e)
            return False

    def pull(
        self,
        addr: str,
        keys: "list[str]",
        expect_generation: Optional[int] = None,
    ) -> Optional[dict]:
        """Fetch resident pages from a peer. Returns the decoded frame dict
        (wire.decode_frame shape, ``found`` keys only) or None on miss /
        stale generation / transport failure / corrupt reply — every None is
        the caller's cue to fall back to the tier path."""
        t0 = time.perf_counter()
        req = {"op": "fabric_pull", "keys": list(keys)}
        if expect_generation is not None:
            req["expect_generation"] = int(expect_generation)
        try:
            hdr, body = self._request(addr, req)
        except Exception as e:  # noqa: BLE001
            logger.debug("fabric pull from %s failed: %s", addr, e)
            return None
        if not hdr.get("ok") or not hdr.get("found") or not body:
            return None
        try:
            frame = decode_frame(body)
        except FabricWireError as e:
            # corrupt reply: quarantine (count + drop), invalidate the probe
            # (the link may be flaky), let the tier path cover these keys
            self.corrupt_frames += 1
            self.probe_cache.invalidate(addr)
            logger.warning("quarantining corrupt fabric pull from %s: %s", addr, e)
            return None
        self.pulled_pages += len(frame["keys"])
        self.pull_hist.observe(time.perf_counter() - t0)
        return frame

    def count_fallback(self, n: int = 1) -> None:
        self.fallbacks += n

    def close(self) -> None:
        with self._meta_lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for cli in clients:
            try:
                cli.close()
            except Exception:  # noqa: BLE001
                pass

    def stats(self) -> dict:
        return {
            "pushed_pages": self.pushed_pages,
            "pulled_pages": self.pulled_pages,
            "fallbacks": self.fallbacks,
            "corrupt_frames": self.corrupt_frames,
            "breaker_opens": self.breaker_opens,
            "probes": self.probe_cache.probes,
            "probe_failures": self.probe_cache.probe_failures,
        }
