"""Fleet-wide KV directory: a content-addressed index of which engine (and
which tier) holds which prefix chunks, hosted by the cache server.

This is the LMCache "enterprise" pattern (PAPERS.md): N engines' DRAM plus the
shared offload tiers become ONE cache. Engines publish directory entries as
their prefix caches change; the router consults the directory to rank backends
*resident > restorable > cold*; a cold engine pulls a fleet-warm prefix
through the existing cache-server blob path before prefill.

The directory is a HINT, never a source of truth: every pulled blob is
CRC-verified by the tier store (kvoffload/serde.py v2 format) and a miss or
corruption falls back to recompute exactly like the warm-restart path. Entries
are fenced by the warm-start generation scheme, so a restarted engine's stale
claims expire instead of poisoning lookups. See docs/kv-directory.md.
"""

from production_stack_tpu.kvdirectory.directory import KVDirectory
from production_stack_tpu.kvdirectory.client import (
    DirectoryClient,
    DirectoryPublisher,
    DirectoryPuller,
)

__all__ = [
    "KVDirectory",
    "DirectoryClient",
    "DirectoryPublisher",
    "DirectoryPuller",
]
