"""The fleet-wide KV directory index.

Maps chunk hash -> per-engine claims: *resident* (the engine holds the page
in HBM) and *shared* (the blob is in the shared cache-server tier, pullable
by ANY engine). Chunk hashes are the same rolling blake2b chain the engine
prefix cache, the warm-start manifests, and the KV-index controller already
use (engine/kv_manager.prefix_hashes), so identity is consistent
router <-> engine <-> tier.

Consistency model (docs/kv-directory.md): the directory is a HINT.

- **Generation fencing**: every engine publishes under a monotonically
  increasing generation (the warm-start generation when --warm-start is on,
  a boot epoch otherwise). A (re)publish with a higher generation expires the
  engine's older-generation entries; a lookup that touches an entry from an
  older generation counts it stale (``stale_hits_total``) and skips it — a
  restarted engine's leftover claims can therefore never win a lookup.
- **Liveness TTL**: an engine silent past ``engine_timeout`` loses its
  *resident* claims (its HBM is presumed gone). *Shared* claims outlive the
  engine — the blob lives in the cache server, not the engine — and are
  verified against the co-hosted blob store (``blob_check``) at lookup time,
  so a capacity-evicted blob stops being advertised immediately.
- Engines always verify: every pulled blob is CRC-checked by the tier store
  and a miss/corruption falls back to recompute (kv_manager contract).

Single-writer by construction: the cache server mutates this from one asyncio
loop. Unit tests drive it synchronously; no locking is needed or provided.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

SNAPSHOT_FORMAT = 1


@dataclass
class DirEntry:
    """One engine's claim on one chunk."""

    resident: bool = False
    shared: bool = False
    generation: int = 0
    depth: int = 0
    score: float = 0.0
    ts: float = 0.0  # wall clock of the last publish touching this entry


@dataclass
class EngineRecord:
    url: str
    page_size: int
    generation: int = 0
    last_seen: float = field(default_factory=time.monotonic)
    chunks: set = field(default_factory=set)  # hash hexes this engine claims


class KVDirectory:
    """In-memory prefix->holders index with generation fencing + TTL."""

    def __init__(
        self,
        engine_timeout: float = 60.0,
        blob_check: Optional[Callable[[str], bool]] = None,
    ):
        self.engine_timeout = engine_timeout
        # co-hosted cache server passes `key in store`: restorable answers
        # then reflect the blobs that actually exist, not stale claims
        self.blob_check = blob_check
        self.engines: dict[str, EngineRecord] = {}  # owned-by: event-loop
        self.chunks: dict[str, dict[str, DirEntry]] = {}  # owned-by: event-loop
        # exported as vllm:kv_directory_* on the cache server metrics surface
        self.publishes_total = 0
        self.withdrawals_total = 0
        self.stale_hits_total = 0
        self.expired_entries_total = 0
        self.lookups_total = 0
        self._stale_publishes = 0

    # -- registration / fencing ----------------------------------------------

    def register(self, url: str, page_size: int, generation: int) -> None:
        rec = self.engines.get(url)
        if rec is None:
            rec = self.engines[url] = EngineRecord(url, page_size, generation)
            logger.info(
                "kv directory: engine %s registered (page_size=%d gen=%d)",
                url, page_size, generation,
            )
            return
        rec.last_seen = time.monotonic()
        rec.page_size = page_size
        if generation > rec.generation:
            self._fence(rec, generation)

    def _fence(self, rec: EngineRecord, generation: int) -> None:
        """A newer incarnation claimed this engine url: expire every entry
        the older generations published (resident claims are definitely gone
        with the old process; shared claims are re-validated by blob_check at
        lookup, but attributing them to a dead generation would misreport
        residency, so they expire too and the new incarnation republishes)."""
        expired = 0
        for h in list(rec.chunks):
            holders = self.chunks.get(h)
            if holders is None:
                rec.chunks.discard(h)
                continue
            e = holders.get(rec.url)
            if e is not None and e.generation < generation:
                del holders[rec.url]
                rec.chunks.discard(h)
                expired += 1
                if not holders:
                    del self.chunks[h]
        if expired:
            logger.info(
                "kv directory: engine %s generation %d -> %d fenced %d "
                "stale entries", rec.url, rec.generation, generation, expired,
            )
        self.expired_entries_total += expired
        rec.generation = generation

    def _alive(self, rec: EngineRecord) -> bool:
        return time.monotonic() - rec.last_seen <= self.engine_timeout

    def expire_dead_engines(self) -> int:
        """Drop RESIDENT claims of engines silent past the TTL (their HBM is
        presumed gone). Shared claims survive — the blob lives in the cache
        server. Called lazily from lookups and the persist loop."""
        expired = 0
        for rec in self.engines.values():
            if self._alive(rec) or not rec.chunks:
                continue
            for h in list(rec.chunks):
                holders = self.chunks.get(h)
                e = holders.get(rec.url) if holders else None
                if e is None:
                    rec.chunks.discard(h)
                    continue
                if e.resident:
                    e.resident = False
                    expired += 1
                if not e.shared:
                    del holders[rec.url]
                    rec.chunks.discard(h)
                    if not holders:
                        del self.chunks[h]
        self.expired_entries_total += expired
        return expired

    # -- publish / withdraw ---------------------------------------------------

    def publish(
        self,
        url: str,
        generation: int,
        entries: Iterable,
        tier: str,
        page_size: int = 0,
    ) -> int:
        """Record claims. ``entries`` is ``[(hash_hex, depth, score), ...]``;
        ``tier`` is "hbm" (resident) or "shared" (blob in the shared store).
        A publish under an OLDER generation than the engine's current one is
        a fenced incarnation's late flush — dropped."""
        rec = self.engines.get(url)
        if rec is None:
            self.register(url, page_size or 0, generation)
            rec = self.engines[url]
        rec.last_seen = time.monotonic()
        if page_size:
            rec.page_size = page_size
        if generation > rec.generation:
            self._fence(rec, generation)
        elif generation < rec.generation:
            self._stale_publishes += 1
            return 0
        resident = tier == "hbm"
        now = time.time()
        n = 0
        for h, depth, score in entries:
            holders = self.chunks.setdefault(h, {})
            e = holders.get(url)
            if e is None:
                e = holders[url] = DirEntry()
                rec.chunks.add(h)
            if resident:
                e.resident = True
            else:
                e.shared = True
            e.generation = generation
            e.depth = int(depth)
            e.score = float(score)
            e.ts = now
            n += 1
        self.publishes_total += n
        return n

    def withdraw(self, url: str, hashes: Iterable[str], scope: str = "resident") -> int:
        """Remove claims. ``scope`` "resident" drops only the HBM claim (the
        blob may still be in the shared tier); "all" removes the engine's
        entry entirely (evict-without-spill: nothing restorable remains)."""
        rec = self.engines.get(url)
        if rec is None:
            return 0
        rec.last_seen = time.monotonic()
        n = 0
        for h in hashes:
            holders = self.chunks.get(h)
            e = holders.get(url) if holders else None
            if e is None:
                continue
            e.resident = False
            if scope == "all":
                e.shared = False
            if not e.resident and not e.shared:
                del holders[url]
                rec.chunks.discard(h)
                if not holders:
                    del self.chunks[h]
            n += 1
        self.withdrawals_total += n
        return n

    def blob_evicted(self, key: str) -> None:
        """The co-hosted cache server evicted (or quarantined) a blob: its
        shared claims are no longer restorable anywhere."""
        holders = self.chunks.get(key)
        if not holders:
            return
        for url in list(holders):
            e = holders[url]
            e.shared = False
            if not e.resident:
                del holders[url]
                rec = self.engines.get(url)
                if rec is not None:
                    rec.chunks.discard(key)
        if not holders:
            del self.chunks[key]

    # -- lookups --------------------------------------------------------------

    def _entry_live(self, url: str, e: DirEntry) -> bool:
        """Generation-fence check at lookup time; stale entries are counted
        and lazily dropped so a restarted engine's claims cannot win."""
        rec = self.engines.get(url)
        if rec is None:
            return False
        if e.generation < rec.generation:
            self.stale_hits_total += 1
            e.resident = e.shared = False
            return False
        return True

    def _shared_available(self, h: str) -> bool:
        holders = self.chunks.get(h)
        if not holders:
            return False
        claimed = any(
            e.shared and self._entry_live(url, e) for url, e in list(holders.items())
        )
        if not claimed:
            return False
        if self.blob_check is not None and not self.blob_check(h):
            # the blob vanished under the claim (capacity eviction raced a
            # publish, or a quarantine): stop advertising it
            self.blob_evicted(h)
            return False
        return True

    def lookup_hashes(self, hashes: list[str]) -> dict:
        """Engine-side pull lookup: per-hash shared-tier availability plus
        contiguous per-engine resident depths (both from chain position 0).
        ``generations`` carries each resident owner's claim generation so a
        fabric pull can be FENCED: the owner rejects a pull tagged with a
        generation older than its own (a reborn owner must not serve pages
        the claim's issuer never wrote)."""
        self.lookups_total += 1
        self.expire_dead_engines()
        shared_flags = [self._shared_available(h) for h in hashes]
        resident: dict[str, int] = {}
        generations: dict[str, int] = {}
        for url, rec in self.engines.items():
            if not self._alive(rec):
                continue
            n = 0
            for h in hashes:
                e = self.chunks.get(h, {}).get(url)
                if e is None or not e.resident or not self._entry_live(url, e):
                    break
                n += 1
            if n:
                resident[url] = n
                generations[url] = rec.generation
        return {
            "shared": shared_flags,
            "resident": resident,
            "generations": generations,
        }

    def lookup_tokens(self, tokens: list[int], salt_hex: str = "") -> dict:
        """Router-side lookup: recompute the chunk-hash chain per registered
        page size (the same scheme as the KV-index controller) and report,
        per engine, the longest contiguous RESIDENT prefix in tokens, plus
        the longest contiguous SHARED (restorable-by-anyone) prefix per page
        size."""
        from production_stack_tpu.engine.kv_manager import prefix_hashes

        self.lookups_total += 1
        self.expire_dead_engines()
        salt = bytes.fromhex(salt_hex) if salt_hex else b""
        by_ps: dict[int, list[str]] = {}
        for rec in self.engines.values():
            ps = rec.page_size
            if ps > 0 and ps not in by_ps:
                by_ps[ps] = [h.hex() for h in prefix_hashes(tokens, ps, salt)]
        engines_out: dict[str, dict] = {}
        for url, rec in self.engines.items():
            if not self._alive(rec) or rec.page_size not in by_ps:
                continue
            chain = by_ps[rec.page_size]
            n = 0
            for h in chain:
                e = self.chunks.get(h, {}).get(url)
                if e is None or not e.resident or not self._entry_live(url, e):
                    break
                n += 1
            if n:
                engines_out[url] = {
                    "resident_tokens": n * rec.page_size,
                    "resident_chunks": n,
                    "page_size": rec.page_size,
                    "generation": rec.generation,
                }
        restorable: dict[str, int] = {}
        for ps, chain in by_ps.items():
            n = 0
            for h in chain:
                if not self._shared_available(h):
                    break
                n += 1
            if n:
                restorable[str(ps)] = n * ps
        return {
            "engines": engines_out,
            "restorable": restorable,
            # every live engine's page size: the router's restorable ranking
            # must not credit a backend with blobs hashed at a page size it
            # cannot consume (chunk identity is page-size-dependent)
            "page_sizes": {
                url: rec.page_size
                for url, rec in self.engines.items()
                if self._alive(rec) and rec.page_size > 0
            },
            "total_tokens": len(tokens),
        }

    def top_prefixes(self, limit: int, page_size: int = 0) -> list:
        """The fleet's warmest RESTORABLE chunks, heads-first (scale-up
        prefetch, docs/migration.md): shared-claimed, blob-backed chunk
        hashes ranked by chain depth ASC then reuse score DESC — a chain can
        only restore from its head, so under a budget the heads are what a
        new engine must pull first. ``page_size`` filters to chunks a
        consumer at that page size can actually use (chunk identity is
        page-size-dependent); 0 keeps all."""
        self.expire_dead_engines()
        scored: list = []
        for h, holders in list(self.chunks.items()):
            best = None
            for url, e in list(holders.items()):
                if not e.shared or not self._entry_live(url, e):
                    continue
                rec = self.engines.get(url)
                if page_size and (rec is None or rec.page_size != page_size):
                    continue
                key = (e.depth, -e.score)
                if best is None or key < best:
                    best = key
            if best is None:
                continue
            if self.blob_check is not None and not self.blob_check(h):
                self.blob_evicted(h)  # vanished under the claim
                continue
            scored.append((best[0], best[1], h))
        scored.sort()
        return [h for _, _, h in scored[: max(0, int(limit))]]

    # -- persistence -----------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-serializable state for offload-tier-backed persistence. The
        loaded copy stays generation-fenced: a reborn engine republishing
        under generation+1 expires its snapshot-restored claims."""
        return {
            "format": SNAPSHOT_FORMAT,
            "ts": time.time(),
            "engines": {
                url: {"page_size": r.page_size, "generation": r.generation}
                for url, r in self.engines.items()
            },
            "chunks": {
                h: {
                    url: [
                        int(e.resident), int(e.shared), e.generation,
                        e.depth, round(e.score, 4),
                    ]
                    for url, e in holders.items()
                }
                for h, holders in self.chunks.items()
            },
        }

    def load_snapshot(self, doc: dict) -> int:
        """Restore a snapshot (cache-server boot). Engines get a fresh TTL
        window to re-appear; resident claims from the snapshot are kept but
        expire via the normal TTL if their engine never returns."""
        if int(doc.get("format", 0)) != SNAPSHOT_FORMAT:
            logger.warning("kv directory: unsupported snapshot format; ignoring")
            return 0
        now = time.monotonic()
        for url, meta in doc.get("engines", {}).items():
            rec = self.engines.setdefault(
                url, EngineRecord(url, int(meta.get("page_size", 0)))
            )
            rec.page_size = int(meta.get("page_size", rec.page_size))
            rec.generation = max(rec.generation, int(meta.get("generation", 0)))
            rec.last_seen = now
        n = 0
        for h, holders in doc.get("chunks", {}).items():
            for url, packed in holders.items():
                rec = self.engines.get(url)
                if rec is None:
                    continue
                resident, shared, gen, depth, score = packed
                if int(gen) < rec.generation:
                    continue  # already fenced when the snapshot was taken
                e = self.chunks.setdefault(h, {}).setdefault(url, DirEntry())
                e.resident = bool(resident)
                e.shared = bool(shared)
                e.generation = int(gen)
                e.depth = int(depth)
                e.score = float(score)
                rec.chunks.add(h)
                n += 1
        logger.info("kv directory: restored %d entries from snapshot", n)
        return n

    def snapshot_json(self) -> bytes:
        return json.dumps(self.snapshot()).encode()

    # -- observability ---------------------------------------------------------

    def stats(self) -> dict:
        entries = sum(len(h) for h in self.chunks.values())
        return {
            "kv_directory_entries": entries,
            "kv_directory_chunks": len(self.chunks),
            "kv_directory_engines": len(self.engines),
            "kv_directory_publishes_total": self.publishes_total,
            "kv_directory_withdrawals_total": self.withdrawals_total,
            "kv_directory_stale_hits_total": self.stale_hits_total,
            "kv_directory_expired_entries_total": self.expired_entries_total,
            "kv_directory_lookups_total": self.lookups_total,
        }

    def dump(self) -> dict:
        """Debug/report surface (scripts/kv_directory_report.py): per-engine
        residency, chain-depth histogram, stale/expired accounting — computed
        server-side so the wire payload stays bounded by fleet size, not
        chunk count."""
        self.expire_dead_engines()
        depth_hist: dict[int, int] = {}
        per_engine: dict[str, dict] = {}
        for url, rec in self.engines.items():
            per_engine[url] = {
                "page_size": rec.page_size,
                "generation": rec.generation,
                "alive": self._alive(rec),
                "resident_chunks": 0,
                "shared_chunks": 0,
            }
        for h, holders in self.chunks.items():
            for url, e in holders.items():
                pe = per_engine.get(url)
                if pe is None:
                    continue
                if e.resident:
                    pe["resident_chunks"] += 1
                    depth_hist[e.depth] = depth_hist.get(e.depth, 0) + 1
                if e.shared:
                    pe["shared_chunks"] += 1
        return {
            "engines": per_engine,
            "depth_histogram": {str(k): v for k, v in sorted(depth_hist.items())},
            **self.stats(),
        }
