"""Directory clients: async (router / report tooling), publisher (engine
background thread), and puller (engine event-loop prefetch).

All three speak the kvoffload frame protocol against the cache server's
``dir_*`` ops (kvoffload/cache_server.py), so one shared server hosts both
the blob tier and the directory that indexes it.
"""

from __future__ import annotations

import asyncio
import queue
import threading
import time
from typing import Optional, Sequence

from production_stack_tpu.kvoffload.protocol import (
    BlockingClient,
    parse_hostport,
    read_frame,
    write_frame,
)
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class DirectoryClient:
    """Asyncio request/response client (router lookup path, report script)."""

    def __init__(self, url: str, timeout: float = 5.0):
        self.host, self.port = parse_hostport(url, default_port=8200)
        self.timeout = timeout
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def _request(self, header: dict) -> dict:
        async with self._lock:
            try:
                if self._writer is None:
                    self._reader, self._writer = await asyncio.wait_for(
                        asyncio.open_connection(self.host, self.port), self.timeout
                    )
                await write_frame(self._writer, header)
                hdr, _ = await asyncio.wait_for(read_frame(self._reader), self.timeout)
                return hdr
            except Exception:
                await self.close()
                raise

    async def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
        self._reader = self._writer = None

    async def lookup(self, tokens: list[int], salt_hex: str = "") -> dict:
        return await self._request(
            {"op": "dir_lookup", "tokens": tokens, "salt": salt_hex}
        )

    async def lookup_hashes(self, hashes: list[str]) -> dict:
        return await self._request({"op": "dir_lookup_hashes", "hashes": hashes})

    async def top_prefixes(self, limit: int, page_size: int = 0) -> dict:
        return await self._request({
            "op": "dir_top_prefixes", "limit": limit, "page_size": page_size,
        })

    async def stats(self) -> dict:
        return await self._request({"op": "dir_stats"})

    async def dump(self) -> dict:
        return await self._request({"op": "dir_dump"})


class DirectoryPublisher:
    """Engine-side dirty-batched publisher.

    The kv_manager hooks (register_filled / evict / proactive_spill) and the
    warm-start spill enqueue claim changes here; a background thread
    coalesces them and flushes one frame batch per ``flush_interval_s`` (the
    engine-stats cadence), so directory upkeep never blocks a serving step
    and a publish storm costs one wire round trip per interval, not one per
    page. Ordering within a flush is preserved (a withdraw enqueued after a
    publish wins)."""

    MAX_PENDING = 16384  # ops; beyond this the oldest are dropped (hint store)

    def __init__(
        self,
        directory_url: str,
        engine_url: str,
        page_size: int,
        generation: int = 1,
        flush_interval_s: float = 5.0,
        shared_enabled: bool = True,
    ):
        self.engine_url = engine_url
        self.page_size = page_size
        self.generation = generation
        self.flush_interval_s = max(0.05, flush_interval_s)
        # shared-tier claims only make sense when the engine writes blobs
        # through to the shared cache server (a disk-only tier is private)
        self.shared_enabled = shared_enabled
        self.publishes = 0
        self.withdrawals = 0
        self.flush_errors = 0
        host, port = parse_hostport(directory_url, default_port=8200)
        self._client = BlockingClient(host, port)
        self._q: queue.Queue = queue.Queue()
        # ENTRY count queued (one batch item can carry a whole working set,
        # so bounding by batch count would leave memory unbounded during a
        # directory outage); guarded by its own lock against the drop-oldest
        # path racing the consumer
        self._queued_entries = 0
        self._entries_lock = threading.Lock()
        self._stop = threading.Event()
        self._registered = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="kv-directory"
        )
        self._thread.start()

    # -- producer side (engine device thread / warm-start) --------------------

    def _put(self, item) -> None:
        with self._entries_lock:
            self._queued_entries += len(item[1])
            while self._queued_entries > self.MAX_PENDING:
                try:  # drop-oldest: the directory is a hint, not a ledger
                    old = self._q.get_nowait()
                except queue.Empty:
                    break
                if old is None:  # never swallow the stop sentinel
                    self._q.put(None)
                    break
                self._queued_entries -= len(old[1])
        self._q.put(item)

    def _take(self, item) -> None:
        """Consumer-side entry accounting for a dequeued batch."""
        with self._entries_lock:
            self._queued_entries -= len(item[1])

    def publish_resident(self, entries: Sequence) -> None:
        """``entries``: (hash bytes, depth, score) of pages now in HBM."""
        if entries:
            self._put(("hbm", [(h.hex(), d, s) for h, d, s in entries]))

    def publish_shared(self, entries: Sequence) -> None:
        """``entries``: (hash bytes, depth, score) whose blobs are CONFIRMED
        in the shared tier (spill / warm-start save confirmations only)."""
        if entries and self.shared_enabled:
            self._put(("shared", [(h.hex(), d, s) for h, d, s in entries]))

    def withdraw(self, hashes: Sequence[bytes], scope: str = "resident") -> None:
        """Evicted from HBM. scope="all" when no restorable blob remains
        (evict-without-spill / dropped beyond the I/O cap)."""
        if hashes:
            self._put(("withdraw-" + scope, [h.hex() for h in hashes]))

    def stop(self) -> None:
        self._stop.set()
        self._q.put(None)
        self._thread.join(timeout=5)
        self._client.close()

    # -- flush thread ----------------------------------------------------------

    def _register(self, force: bool = False) -> None:
        if force or not self._registered:
            self._client.request({
                "op": "dir_register", "url": self.engine_url,
                "page_size": self.page_size,
                "generation": self.generation,
            })
            self._registered = True

    def _run(self) -> None:
        pending: list = []
        last_flush = time.monotonic()
        try:
            # eager best-effort register: a COLD engine publishes nothing,
            # but the fleet (directory dumps, liveness TTL) should still see
            # it; failures fall back to register-on-first-flush
            self._register()
        except Exception as e:  # noqa: BLE001 - directory may not be up yet
            logger.warning("kv directory register failed (will retry): %s", e)
        while True:
            wait = max(0.05, self.flush_interval_s - (time.monotonic() - last_flush))
            try:
                item = self._q.get(timeout=wait)
            except queue.Empty:
                item = False  # timeout tick
            if item is None:
                self._flush(pending)  # final drain on stop
                return
            if item:
                self._take(item)
                pending.append(item)
            while True:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._flush(pending)
                    return
                self._take(nxt)
                pending.append(nxt)
            if time.monotonic() - last_flush >= self.flush_interval_s:
                if pending:
                    if self._flush(pending):
                        pending = []
                    else:
                        # outage retention is ENTRY-bounded too: keep the
                        # newest batches whose summed entries fit the cap
                        pending = self._trim_entries(pending, self.MAX_PENDING)
                else:
                    # idle heartbeat: re-register so the directory's liveness
                    # TTL never expires a healthy-but-quiet engine's claims
                    try:
                        self._register(force=True)
                    except Exception:  # noqa: BLE001 - retried next tick
                        self._registered = False
                last_flush = time.monotonic()

    @staticmethod
    def _trim_entries(batches: list, cap: int) -> list:
        """Newest suffix of ``batches`` whose summed entry count fits ``cap``."""
        total = 0
        for i in range(len(batches) - 1, -1, -1):
            total += len(batches[i][1])
            if total > cap:
                return batches[i + 1:]
        return batches

    def _merge(self, pending: list) -> list:
        """Coalesce adjacent same-kind batches (order across kinds kept)."""
        merged: list = []
        for kind, items in pending:
            if merged and merged[-1][0] == kind:
                merged[-1][1].extend(items)
            else:
                merged.append((kind, list(items)))
        return merged

    def _flush(self, pending: list) -> bool:
        if not pending:
            return True
        try:
            self._register()
            for kind, items in self._merge(pending):
                if kind in ("hbm", "shared"):
                    self._client.request({
                        "op": "dir_publish", "url": self.engine_url,
                        "generation": self.generation, "tier": kind,
                        "page_size": self.page_size, "entries": items,
                    })
                    self.publishes += len(items)
                else:
                    self._client.request({
                        "op": "dir_withdraw", "url": self.engine_url,
                        "hashes": items,
                        "scope": kind.split("-", 1)[1],
                    })
                    self.withdrawals += len(items)
            return True
        except Exception as e:  # noqa: BLE001 - directory down: retry next tick
            self.flush_errors += 1
            self._registered = False  # re-register on reconnect
            logger.warning("kv directory flush failed: %s", e)
            return False

    def stats(self) -> dict:
        return {
            "kv_directory_publishes_total": self.publishes,
            "kv_directory_withdrawals_total": self.withdrawals,
            "kv_directory_flush_errors_total": self.flush_errors,
        }


class DirectoryPuller:
    """Engine event-loop side of the cross-engine pull.

    On request admission (engine.generate, BEFORE the sequence reaches the
    scheduler) it asks the directory how much of the prompt's chain beyond
    the local prefix match is restorable from the shared tier, and prefetches
    those blobs into the LOCAL host tiers off the event loop. The later
    device-thread restore (kv_manager._extend_from_offload) then finds them
    with a local read instead of paying a per-chunk remote round trip inside
    scheduling. Misses and corrupt blobs degrade to recompute — the store
    CRC-verifies and quarantines on get."""

    def __init__(
        self,
        directory_url: str,
        kv,
        store,
        page_size: int,
        max_pages: int = 256,
        timeout: float = 2.0,
        backoff_s: float = 30.0,
    ):
        self.url = directory_url
        self.kv = kv
        self.store = store
        self.page_size = page_size
        self.max_pages = max_pages
        self.timeout = timeout
        self.backoff_s = backoff_s
        self.lookups = 0
        self.lookup_hits = 0
        self.pulled_pages = 0
        self.fabric_pulled_pages = 0
        self.errors = 0
        self._client: Optional[DirectoryClient] = None
        self._skip_until = 0.0
        # fabric resident-pull path (docs/kv-fabric.md): fetch RESIDENT-only
        # pages straight from the owning engine — zero shared-tier I/O — with
        # the tier walk as fallback. Armed by the engine via enable_fabric.
        self._fabric = None
        self._serde = None
        self.self_url: Optional[str] = None
        self._fabric_addrs: "dict[str, tuple[Optional[str], float]]" = {}

    FABRIC_ADDR_TTL_S = 60.0

    def enable_fabric(self, fabric_client, self_url: str, serde=None) -> None:
        """Arm the fabric pull path: ``fabric_client`` is the engine's
        KVFabricClient (its counters and breaker are shared with the other
        movers); ``self_url`` keeps this engine from "pulling" from itself;
        ``serde`` converts pulled frames into this engine's tier blobs
        (defaults to the engine serde the store was built with)."""
        self._fabric = fabric_client
        self.self_url = self_url
        if serde is None:
            from production_stack_tpu.kvoffload.serde import get_serde

            serde = get_serde("naive")
        self._serde = serde

    async def maybe_prefetch(self, tokens: Sequence[int], salt: bytes = b"") -> int:
        from production_stack_tpu.engine.kv_manager import prefix_hashes

        if time.monotonic() < self._skip_until:
            return 0
        hashes = prefix_hashes(tokens, self.page_size, salt)
        if not hashes:
            return 0
        # local-prefix hint: dict probes only (the device thread owns the
        # manager; a racy read here can only cost an unnecessary prefetch)
        local = 0
        for h in hashes:
            if h in self.kv.hash_to_page:
                local += 1
            else:
                break
        missing = hashes[local:]
        if not missing:
            return 0
        self.lookups += 1
        try:
            if self._client is None:
                self._client = DirectoryClient(self.url, timeout=self.timeout)
            res = await self._client.lookup_hashes([h.hex() for h in missing])
        except Exception as e:  # noqa: BLE001 - directory down: back off
            self.errors += 1
            self._client = None
            self._skip_until = time.monotonic() + self.backoff_s
            logger.warning("kv directory lookup failed (backing off): %s", e)
            return 0
        flags = res.get("shared") or []
        n = 0
        for f in flags:
            if not f or n >= self.max_pages:
                break
            n += 1
        if self._fabric is not None:
            # fabric resident pull: fetch straight from the engine that
            # holds the deepest contiguous RESIDENT prefix (a resident hit
            # used to be routing-only — these pages may not exist in the
            # shared tier at all). Generation-fenced: the pull carries the
            # claim's generation and a reborn owner rejects it. Any miss
            # falls through to the shared-tier walk below.
            resident = res.get("resident") or {}
            gens = res.get("generations") or {}
            owner, depth = None, 0
            for url, d in resident.items():
                if url != self.self_url and int(d) > depth:
                    owner, depth = url, int(d)
            depth = min(depth, self.max_pages, len(missing))
            if owner is not None and depth > 0:
                keys = [h.hex() for h in missing[:depth]]
                loop = asyncio.get_running_loop()
                got = await loop.run_in_executor(
                    None, self._fabric_fetch, owner, gens.get(owner), keys
                )
                if got:
                    self.lookup_hits += 1
                    self.fabric_pulled_pages += got
                    self.pulled_pages += got
                    return got
        if n == 0:
            return 0
        self.lookup_hits += 1
        keys = [h.hex() for h in missing[:n]]
        loop = asyncio.get_running_loop()
        got = await loop.run_in_executor(None, self._fetch, keys)
        self.pulled_pages += got
        return got

    def _owner_fabric_addr(self, owner_url: str) -> Optional[str]:
        """Resolve (and cache) an owner's fabric listener via its
        GET /kv_fabric. Negative results are cached too — an owner without
        the fabric enabled must not cost an HTTP round trip per admission."""
        addr, until = self._fabric_addrs.get(owner_url, (None, 0.0))
        if until > time.monotonic():
            return addr
        resolved = None
        try:
            import json as json_mod
            import urllib.request

            with urllib.request.urlopen(
                owner_url.rstrip("/") + "/kv_fabric", timeout=self.timeout
            ) as r:
                info = json_mod.loads(r.read())
            if info.get("enabled", True):
                resolved = info.get("addr")
        except Exception as e:  # noqa: BLE001 - fabric optional per owner
            logger.debug("fabric addr resolve failed for %s: %s", owner_url, e)
        self._fabric_addrs[owner_url] = (
            resolved, time.monotonic() + self.FABRIC_ADDR_TTL_S
        )
        return resolved

    def _fabric_fetch(
        self, owner_url: str, generation: Optional[int], keys: "list[str]"
    ) -> int:
        """Pull resident pages from the owning engine over the fabric
        (executor thread) and land them as LOCAL tier blobs. Returns pages
        landed; 0 sends the caller to the shared-tier fallback (counted as a
        fabric fallback)."""
        addr = self._owner_fabric_addr(owner_url)
        if addr is None:
            return 0
        frame = self._fabric.pull(
            addr, keys,
            expect_generation=int(generation) if generation is not None else None,
        )
        if frame is None:
            # miss/stale/outage: drop the cached addr (the owner may have
            # restarted on a new port) and count the tier fallback
            self._fabric_addrs.pop(owner_url, None)
            self._fabric.count_fallback(len(keys))
            return 0
        from production_stack_tpu.kvfabric.wire import frame_to_blobs

        n = 0
        try:
            for key, blob in frame_to_blobs(frame, self._serde):
                self.store.put_local(key, blob)
                n += 1
        except Exception:  # noqa: BLE001 - partial landing is still progress
            logger.exception("fabric pull landing failed after %d pages", n)
        return n

    def _fetch(self, keys: list[str]) -> int:
        """Pull blobs into the local tiers (executor thread). ``store.get``
        walks local->remote, CRC-verifies, and promotes remote hits into the
        CPU tier; a key already local is free."""
        n = 0
        for k in keys:
            try:
                if self.store.contains_local(k):
                    n += 1
                elif self.store.get(k) is not None:
                    n += 1
                else:
                    break  # chain broken: later chunks are unrestorable anyway
            except Exception:  # noqa: BLE001 - tier error: recompute covers it
                logger.exception("kv directory prefetch failed for %s", k)
                break
        return n

    def stats(self) -> dict:
        return {
            "kv_directory_lookups_total": self.lookups,
            "kv_directory_lookup_hits_total": self.lookup_hits,
            "kv_directory_pulled_pages_total": self.pulled_pages,
        }
