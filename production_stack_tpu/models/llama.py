"""Llama family (Llama 2/3/3.x, and by config also Mistral/Qwen2-sans-bias) as
pure functional JAX.

TPU-first choices:
- Layers are *stacked*: every per-layer weight is one array with a leading
  ``[num_layers, ...]`` axis and the decoder runs as a single ``lax.scan``.
  One layer gets traced/compiled instead of 32, and the KV page pools ride the
  scan as per-layer slices ``xs``/``ys`` (compile time and HBM layout both win).
- bfloat16 weights/activations, fp32 softmax/norm statistics.
- No data-dependent Python control flow: padding is handled by -1 positions
  (dropped KV writes, masked attention), so one compiled program serves any
  ragged batch within a (batch, pages) bucket.

Reference parity: the stack's engine contract serves `meta-llama/Llama-3.1-8B-
Instruct` (reference README.md:20-46) and `facebook/opt-125m` (CPU smoke,
tutorials/assets/values-01-minimal-example.yaml); see models/opt.py for the
latter.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from production_stack_tpu.ops.attention import flash_attention, gather_kv_pages, write_kv_pages
from production_stack_tpu.ops.norms import rms_norm
from production_stack_tpu.ops.rope import RopeScaling, apply_rope, rope_cos_sin


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    rope_scaling: Optional[RopeScaling] = None
    rms_norm_eps: float = 1e-5
    max_model_len: int = 8192
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # decode attention implementation: "auto" (ModelRunner resolves), "xla"
    # (gather + flash, partitions under GSPMD), "pallas" (page-streaming
    # kernel, single-shard meshes), "pallas_interpret" (tests on CPU).
    # "auto" outside a runner falls back to the XLA path.
    attn_impl: str = "auto"

    @staticmethod
    def from_hf_config(cfg: dict) -> "LlamaConfig":
        """Build from a HuggingFace `config.json` dict (LlamaForCausalLM etc.)."""
        scaling = None
        rs = cfg.get("rope_scaling") or None
        if rs and rs.get("rope_type", rs.get("type")) == "llama3":
            scaling = RopeScaling(
                factor=rs.get("factor", 8.0),
                low_freq_factor=rs.get("low_freq_factor", 1.0),
                high_freq_factor=rs.get("high_freq_factor", 4.0),
                original_max_position=rs.get("original_max_position_embeddings", 8192),
            )
        hidden = cfg["hidden_size"]
        heads = cfg["num_attention_heads"]
        return LlamaConfig(
            vocab_size=cfg["vocab_size"],
            hidden_size=hidden,
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=cfg.get("num_key_value_heads", heads),
            head_dim=cfg.get("head_dim", hidden // heads),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_scaling=scaling,
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_model_len=cfg.get("max_position_embeddings", 8192),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
        )


# Small presets used by tests, the benchmark, and the graft entry.
PRESETS: dict[str, LlamaConfig] = {
    "llama-3-8b": LlamaConfig(),
    "llama-3.2-1b": LlamaConfig(
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        rope_scaling=RopeScaling(factor=32.0),
        tie_word_embeddings=True,
    ),
    "llama-debug": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        rope_theta=10000.0,
        max_model_len=256,
    ),
}


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Random-normal initialized parameter tree (layer-stacked)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    NH, KH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 7)
    scale = H**-0.5
    params = {
        "embed": normal(k_embed, (cfg.vocab_size, H), scale),
        "layers": {
            "attn_norm": jnp.ones((L, H), cfg.dtype),
            "wq": normal(ks[0], (L, H, NH * D), scale),
            "wk": normal(ks[1], (L, H, KH * D), scale),
            "wv": normal(ks[2], (L, H, KH * D), scale),
            "wo": normal(ks[3], (L, NH * D, H), (NH * D) ** -0.5),
            "mlp_norm": jnp.ones((L, H), cfg.dtype),
            "w_gate": normal(ks[4], (L, H, I), scale),
            "w_up": normal(ks[5], (L, H, I), scale),
            "w_down": normal(ks[6], (L, I, H), I**-0.5),
        },
        "final_norm": jnp.ones((H,), cfg.dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = normal(k_head, (H, cfg.vocab_size), scale)
    return params


def init_kv_pages(
    cfg: LlamaConfig, num_pages: int, page_size: int, dtype=None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Layer-stacked page pools: [L, num_pages, page_size, KH, D]."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def forward(
    params: dict,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lens: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One forward step (prefill chunk or decode) with paged KV.

    Args:
      input_ids:  [B, T] int32 (T=1 for decode; padded rows have position -1).
      positions:  [B, T] absolute positions, -1 for padding.
      k_pages/v_pages: [L, P, page_size, KH, D] pools (donate for in-place).
      page_table: [B, max_pages] physical page ids per sequence.
      kv_lens:    [B] total valid KV length *including* this step's tokens.

    Returns (logits[B, V] for each sequence's last valid token,
             k_pages, v_pages updated).
    """
    B, T = input_ids.shape
    x = params["embed"][input_ids].astype(cfg.dtype)  # [B, T, H]
    cos, sin = rope_cos_sin(
        jnp.maximum(positions, 0), cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    def layer(x, layer_in):
        lp, kp, vp = layer_in  # per-layer params and page pools
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q = (h @ lp["wq"]).reshape(B, T, cfg.num_heads, cfg.head_dim)
        k = (h @ lp["wk"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        kp, vp = write_kv_pages(kp, vp, k.astype(kp.dtype), v.astype(vp.dtype), page_table, positions)
        if T == 1 and cfg.attn_impl.startswith("pallas"):
            # decode: stream pages HBM->VMEM, no gather materialization
            from production_stack_tpu.ops.pallas.paged_attention import (
                ragged_paged_attention_decode,
            )

            attn = ragged_paged_attention_decode(
                q[:, 0], kp, vp, page_table, kv_lens,
                interpret=cfg.attn_impl == "pallas_interpret",
            )[:, None]
        else:
            kc, vc = gather_kv_pages(kp, vp, page_table)
            attn = flash_attention(q, kc, vc, q_positions=positions, kv_lens=kv_lens)
        x = x + attn.reshape(B, T, -1) @ lp["wo"]
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + (jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])) @ lp["w_down"]
        return x, (kp, vp)

    x, (k_pages, v_pages) = lax.scan(layer, x, (params["layers"], k_pages, v_pages))

    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    # Select each sequence's last valid token before the vocab projection so the
    # logits tensor is [B, V], not [B, T, V] (a 2 GB save at V=128k, T=1k).
    last_idx = jnp.maximum(jnp.sum(positions >= 0, axis=1) - 1, 0)  # [B]
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]  # [B, H]
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    logits = (x_last @ head).astype(jnp.float32)
    return logits, k_pages, v_pages
