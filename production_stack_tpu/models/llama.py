"""Llama superfamily (Llama 2/3/3.x, Mistral, Qwen2/2.5, Mixtral-MoE) as
pure functional JAX.

One forward covers the whole family through static config switches (resolved at
trace time, so each variant still compiles to a single straight-line program):
``attention_bias`` (Qwen2), ``sliding_window`` (Mistral/Qwen2),
``num_experts>0`` (Mixtral sparse-MoE MLP with top-k routing; expert weights
carry a leading [E] axis sharded on the ``ep`` mesh axis — SURVEY.md §2.3
"mesh axis reserved" made real).

TPU-first choices:
- Layers are *stacked*: every per-layer weight is one array with a leading
  ``[num_layers, ...]`` axis and the decoder runs as a single ``lax.scan``.
  One layer gets traced/compiled instead of 32, and the KV page pools ride the
  scan as per-layer slices ``xs``/``ys`` (compile time and HBM layout both win).
- bfloat16 weights/activations, fp32 softmax/norm statistics.
- No data-dependent Python control flow: padding is handled by -1 positions
  (dropped KV writes, masked attention), so one compiled program serves any
  ragged batch within a (batch, pages) bucket.

Reference parity: the stack's engine contract serves `meta-llama/Llama-3.1-8B-
Instruct` (reference README.md:20-46) and `facebook/opt-125m` (CPU smoke,
tutorials/assets/values-01-minimal-example.yaml); see models/opt.py for the
latter.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from production_stack_tpu.ops.attention import (
    flash_attention,
    gather_kv_pages,
    stale_kv_positions,
    write_kv_pages,
    write_kv_pages_all_layers,
)
from production_stack_tpu.ops.norms import rms_norm
from production_stack_tpu.ops.rope import RopeScaling, apply_rope, rope_cos_sin
from production_stack_tpu.parallel import compat


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    rope_scaling: Optional[RopeScaling] = None
    rms_norm_eps: float = 1e-5
    max_model_len: int = 8192
    tie_word_embeddings: bool = False
    attention_bias: bool = False          # Qwen2: bias on q/k/v projections
    sliding_window: Optional[int] = None  # Mistral/Qwen2: windowed attention
    num_experts: int = 0                  # Mixtral: >0 switches MLP to sparse MoE
    num_experts_per_tok: int = 2
    dtype: Any = jnp.bfloat16
    # decode attention implementation: "auto" (ModelRunner resolves), "xla"
    # (gather + flash, partitions under GSPMD), "pallas" (page-streaming
    # kernel, single-shard meshes), "pallas_interpret" (tests on CPU).
    # "auto" outside a runner falls back to the XLA path.
    attn_impl: str = "auto"
    # KV write placement. "pre": write each layer's K/V into its pool slice
    # before attending (pool updates ride the layer scan — simple, but XLA
    # materializes pool-sized copies per layer). "post" (default): attend
    # over the stale pool + in-register current-chunk K/V, stack per-layer
    # K/V as scan outputs, and write ALL layers with one batched scatter
    # after the scan (donated pools update in place — no per-layer copies;
    # measured -26% per decode burst on v5e).
    kv_write_mode: str = "post"
    # decode-kernel memory pipeline tuning (0 = kernel auto; see
    # ops/pallas/paged_attention.py and engine/config.py): pages per packed
    # grid cell, and DMA-ring depth (page copies kept in flight)
    decode_pages_per_block: int = 0
    decode_prefetch_pages: int = 0
    # prefill-kernel memory pipeline tuning (0 = kernel auto; see
    # ops/pallas/prefill_attention.py): KV pages landed contiguously per
    # packed grid cell (one wide matmul each), and how many page DMAs stay
    # in flight ahead of the cell being consumed
    prefill_pages_per_block: int = 0
    prefill_prefetch_pages: int = 0
    # fused paged-KV write: the prefill kernel scatters the chunk's K/V
    # into its pool pages in-kernel (pools aliased input->output), so the
    # layer scan stops stacking per-layer K/V and the post-scan
    # write_kv_pages_all_layers pass disappears from the prefill path
    prefill_fused_kv_write: bool = True
    # KV cache dtype: "auto" (= cfg.dtype), "bf16"/"fp16" (explicit fp), or
    # "int8" — quantized pages with per-page per-kv-head scales in a
    # parallel scales pool (ops/quant.py): HALF the HBM bytes every decode
    # step streams and double the effective pool capacity. Dequantization
    # happens inside the kernels' VMEM copy rings (and at the XLA gather on
    # the fallback path); quantization inside the fused prefill write and
    # on the decode feedback commit. Requires kv_write_mode="post";
    # ModelRunner builds the scales pools and threads them as ``kv_scales``.
    kv_cache_dtype: str = "auto"

    @staticmethod
    def from_hf_config(cfg: dict) -> "LlamaConfig":
        """Build from a HuggingFace `config.json` dict. Handles
        LlamaForCausalLM, MistralForCausalLM, Qwen2ForCausalLM, and
        MixtralForCausalLM (arch read from `architectures[0]`)."""
        arch = (cfg.get("architectures") or ["LlamaForCausalLM"])[0]
        scaling = None
        rs = cfg.get("rope_scaling") or None
        if rs and rs.get("rope_type", rs.get("type")) == "llama3":
            scaling = RopeScaling(
                factor=rs.get("factor", 8.0),
                low_freq_factor=rs.get("low_freq_factor", 1.0),
                high_freq_factor=rs.get("high_freq_factor", 4.0),
                original_max_position=rs.get("original_max_position_embeddings", 8192),
            )
        hidden = cfg["hidden_size"]
        heads = cfg["num_attention_heads"]
        # Qwen2 always biases q/k/v; Mistral/Qwen2 may window attention.
        window = cfg.get("sliding_window")
        if arch.startswith("Qwen2") and not cfg.get("use_sliding_window", False):
            window = None
        return LlamaConfig(
            vocab_size=cfg["vocab_size"],
            hidden_size=hidden,
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=cfg.get("num_key_value_heads", heads),
            head_dim=cfg.get("head_dim") or hidden // heads,
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_scaling=scaling,
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_model_len=cfg.get("max_position_embeddings", 8192),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            attention_bias=cfg.get("attention_bias", arch.startswith("Qwen2")),
            sliding_window=window,
            num_experts=cfg.get("num_local_experts", 0)
            if arch.startswith("Mixtral")
            else 0,
            num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
        )


# Small presets used by tests, the benchmark, and the graft entry.
PRESETS: dict[str, LlamaConfig] = {
    "llama-3-8b": LlamaConfig(),
    "llama-3.2-1b": LlamaConfig(
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        rope_scaling=RopeScaling(factor=32.0),
        tie_word_embeddings=True,
    ),
    "mistral-7b": LlamaConfig(
        vocab_size=32000,
        rope_theta=10000.0,
        sliding_window=4096,
        max_model_len=32768,
    ),
    "qwen2.5-7b": LlamaConfig(
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        attention_bias=True,
        max_model_len=32768,
    ),
    "mixtral-8x7b": LlamaConfig(
        vocab_size=32000,
        rope_theta=1000000.0,
        num_experts=8,
        num_experts_per_tok=2,
        max_model_len=32768,
    ),
    "llama-debug": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        rope_theta=10000.0,
        max_model_len=256,
    ),
}


def _debug_variant(**kw) -> LlamaConfig:
    import dataclasses as _dc

    return _dc.replace(PRESETS["llama-debug"], **kw)


PRESETS["qwen2-debug"] = _debug_variant(attention_bias=True)
# tp=4-shardable debug preset: 8 q / 4 kv heads divide over tp in {1, 2, 4}
# so the paged pool's kv-head axis genuinely shards per chip (llama-debug's
# 2 kv heads cap at tp=2) — the CPU-mesh stand-in for the flagship
# llama-3.2-1b (32 q / 8 kv heads) tensor-parallel serving path
PRESETS["llama-debug-4kv"] = _debug_variant(num_heads=8, num_kv_heads=4)
# f32 twin for tp token-identity tests: tp changes all-reduce partial-sum
# order, and on RANDOM weights (near-flat logits) bf16 reduction noise flips
# greedy near-ties — f32 keeps tp=1/2/4 logits equal to ~1e-6, so greedy
# output is genuinely token-identical across tp shapes
PRESETS["llama-debug-4kv-f32"] = _debug_variant(
    num_heads=8, num_kv_heads=4, dtype=jnp.float32
)
PRESETS["mistral-debug"] = _debug_variant(sliding_window=8)
PRESETS["mixtral-debug"] = _debug_variant(num_experts=4, num_experts_per_tok=2)


def init_params(cfg: LlamaConfig, key: jax.Array) -> dict:
    """Random-normal initialized parameter tree (layer-stacked)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    NH, KH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 8)
    scale = H**-0.5
    layers: dict = {
        "attn_norm": jnp.ones((L, H), cfg.dtype),
        "wq": normal(ks[0], (L, H, NH * D), scale),
        "wk": normal(ks[1], (L, H, KH * D), scale),
        "wv": normal(ks[2], (L, H, KH * D), scale),
        "wo": normal(ks[3], (L, NH * D, H), (NH * D) ** -0.5),
        "mlp_norm": jnp.ones((L, H), cfg.dtype),
    }
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((L, NH * D), cfg.dtype)
        layers["bk"] = jnp.zeros((L, KH * D), cfg.dtype)
        layers["bv"] = jnp.zeros((L, KH * D), cfg.dtype)
    if cfg.num_experts:
        E = cfg.num_experts
        layers["moe_router"] = normal(ks[7], (L, H, E), scale)
        layers["moe_gate"] = normal(ks[4], (L, E, H, I), scale)
        layers["moe_up"] = normal(ks[5], (L, E, H, I), scale)
        layers["moe_down"] = normal(ks[6], (L, E, I, H), I**-0.5)
    else:
        layers["w_gate"] = normal(ks[4], (L, H, I), scale)
        layers["w_up"] = normal(ks[5], (L, H, I), scale)
        layers["w_down"] = normal(ks[6], (L, I, H), I**-0.5)
    params = {
        "embed": normal(k_embed, (cfg.vocab_size, H), scale),
        "layers": layers,
        "final_norm": jnp.ones((H,), cfg.dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = normal(k_head, (H, cfg.vocab_size), scale)
    return params


def init_kv_pages(
    cfg: LlamaConfig, num_pages: int, page_size: int, dtype=None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Layer-stacked page pools: [L, num_pages, page_size, KH, D]."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def lora_dims(cfg: LlamaConfig) -> dict[str, tuple[int, int]]:
    """(in_dim, out_dim) per LoRA-targetable projection."""
    H, I = cfg.hidden_size, cfg.intermediate_size
    dims = {
        "wq": (H, cfg.num_heads * cfg.head_dim),
        "wk": (H, cfg.num_kv_heads * cfg.head_dim),
        "wv": (H, cfg.num_kv_heads * cfg.head_dim),
        "wo": (cfg.num_heads * cfg.head_dim, H),
    }
    if not cfg.num_experts:  # MoE expert weights are not LoRA targets
        dims.update({"w_gate": (H, I), "w_up": (H, I), "w_down": (I, H)})
    return dims


def init_lora_buffers(
    cfg: LlamaConfig,
    max_loras: int,
    max_rank: int,
    targets: tuple[str, ...] = ("wq", "wk", "wv", "wo"),
) -> dict:
    """Slot-stacked LoRA buffers for batched multi-adapter serving.

    Layout is TPU-first: per target ``a_<t>: [L, S, in, R]`` and
    ``b_<t>: [L, S, R, out]`` with the layer axis leading so the buffers ride
    the decoder's ``lax.scan`` alongside the base weights, and the slot axis
    ``S`` gathered per sequence at trace time (one compiled program serves a
    batch mixing any adapters — the TPU analogue of punica/S-LoRA batched
    LoRA, which the reference stack reaches through vLLM's ``--enable-lora``,
    helm/templates/deployment-vllm-multi.yaml:197-207 in /root/reference).

    Slot 0 is reserved for "no adapter" and stays all-zero; ``scale`` is the
    per-slot ``alpha / r`` factor.
    """
    dims = lora_dims(cfg)
    unknown = set(targets) - set(dims)
    if unknown:
        raise ValueError(f"unknown LoRA targets {sorted(unknown)}; known: {sorted(dims)}")
    L, S, R = cfg.num_layers, max_loras, max_rank
    layers = {}
    for t in targets:
        din, dout = dims[t]
        layers["a_" + t] = jnp.zeros((L, S, din, R), cfg.dtype)
        layers["b_" + t] = jnp.zeros((L, S, R, dout), cfg.dtype)
    return {"layers": layers, "scale": jnp.zeros((S,), jnp.float32)}


def _qkv(h, lp, cfg: LlamaConfig, B: int, T: int, cos, sin, proj):
    """Shared q/k/v projection + bias + rope (forward and encode paths)."""
    q = proj(h, "wq").reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = proj(h, "wk").reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = proj(h, "wv").reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    if cfg.attention_bias:
        q = q + lp["bq"].reshape(cfg.num_heads, cfg.head_dim)
        k = k + lp["bk"].reshape(cfg.num_kv_heads, cfg.head_dim)
        v = v + lp["bv"].reshape(cfg.num_kv_heads, cfg.head_dim)
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v


def _mlp_residual(x, lp, cfg: LlamaConfig, proj):
    """Shared post-attention MLP (dense or MoE) residual block."""
    h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    if cfg.num_experts:
        return x + _moe_block(h, lp, cfg)
    return x + proj(jax.nn.silu(proj(h, "w_gate")) * proj(h, "w_up"), "w_down")


def _plain_proj(lp):
    return lambda h, name: h @ lp[name]


def encode(
    params: dict,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
) -> jnp.ndarray:
    """Pooled-embedding forward: one dense causal pass (no KV pages), masked
    mean-pool over valid tokens of the final hidden layer, L2-normalized.

    Serves /v1/embeddings, /v1/rerank, /v1/score — surface parity with the
    reference router's passthrough endpoints (routers/main_router.py:45-231 in
    /root/reference), which assume an engine that can embed.

    Args:
      input_ids: [B, T] int32, padded rows have position -1.
      positions: [B, T] absolute positions, -1 for padding.
    Returns [B, H] float32 unit vectors.
    """
    B, T = input_ids.shape
    x = params["embed"][input_ids].astype(cfg.dtype)
    cos, sin = rope_cos_sin(
        jnp.maximum(positions, 0), cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    valid = positions >= 0  # [B, T]

    def layer(x, lp):
        proj = _plain_proj(lp)
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, lp, cfg, B, T, cos, sin, proj)
        attn = flash_attention(
            q, k, v, q_positions=positions, kv_lens=jnp.sum(valid, axis=1),
            window=cfg.sliding_window,
        )
        x = x + proj(attn.reshape(B, T, -1), "wo")
        return _mlp_residual(x, lp, cfg, proj), None

    x, _ = lax.scan(layer, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps).astype(jnp.float32)
    mask = valid.astype(jnp.float32)[..., None]
    pooled = (x * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def _moe_block(h: jnp.ndarray, lp: dict, cfg: LlamaConfig) -> jnp.ndarray:
    """Mixtral sparse-MoE MLP, computed densely over experts.

    Routing follows HF Mixtral: softmax over all experts, take top-k, renormalize.
    The dispatch is *dense* — every token multiplies every expert, with
    non-selected experts zeroed by the gate — which XLA maps cleanly onto the
    MXU with static shapes. With expert weights sharded on the ``ep`` mesh axis
    each device computes only its E/ep experts and the final contraction over E
    becomes one psum over ICI (classic expert parallelism). A sort-based
    capacity dispatch (token-choice) is the future optimization for large E at
    small batch; at serving batch sizes the dense form wins on compile
    simplicity and avoids ragged all-to-alls.
    """
    B, T, H = h.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    router_logits = (h @ lp["moe_router"]).astype(jnp.float32)     # [B, T, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    topw, topi = lax.top_k(probs, K)                               # [B, T, K]
    topw = topw / jnp.maximum(topw.sum(-1, keepdims=True), 1e-9)
    # scatter the renormalized top-k weights back to a dense [B, T, E] gate
    gate = (jax.nn.one_hot(topi, E, dtype=jnp.float32) * topw[..., None]).sum(-2)
    g = jnp.einsum("bth,ehi->btei", h, lp["moe_gate"])
    u = jnp.einsum("bth,ehi->btei", h, lp["moe_up"])
    y = jax.nn.silu(g) * u * gate.astype(h.dtype)[..., None]
    return jnp.einsum("btei,eih->bth", y, lp["moe_down"])


# mesh axes this family's forward actually implements (runner gates sp/pp
# on this — a mesh kwarg alone doesn't imply ring attention or pipelining)
MESH_AXES = ("dp", "tp", "sp", "ep", "pp")


def forward(
    params: dict,
    cfg: LlamaConfig,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lens: jnp.ndarray,
    lora: Optional[dict] = None,
    lora_ids: Optional[jnp.ndarray] = None,
    all_logits: bool = False,
    mesh=None,
    kv_burst: Optional[tuple] = None,
    kv_scales: Optional[tuple] = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One forward step (prefill chunk or decode) with paged KV.

    Args:
      input_ids:  [B, T] int32 (T=1 for decode; padded rows have position -1).
      positions:  [B, T] absolute positions, -1 for padding.
      k_pages/v_pages: [L, P, page_size, KH, D] pools (donate for in-place).
      page_table: [B, max_pages] physical page ids per sequence.
      kv_lens:    [B] total valid KV length *including* this step's tokens.
      lora:       optional ``init_lora_buffers`` tree for batched multi-LoRA.
      lora_ids:   [B] int32 adapter slot per sequence (0 = base model).
      all_logits: static; True returns logits for *every* position (used by
                  speculative verify, which scores k draft tokens at once).
      mesh:       serving mesh, passed by ModelRunner when it has sp>1 (ring-
                  attention prefill over the sequence axis) or pp>1 (layer
                  stack pipelined over stages); None = plain GSPMD tp/dp.
      kv_burst:   deferred-scatter decode mode (T=1, kv_write_mode='post'
                  only): (k_acc [L, B, C, KH, D], v_acc, counts [B]) — the
                  burst's accumulated K/V windows plus how many entries are
                  valid per row. The POOLS ARE NOT WRITTEN: attention reads
                  pool slots < kv_lens - (counts+1) plus the window, and the
                  return value is (logits, k_acc', v_acc') with the current
                  token appended at slot ``counts``. The caller commits once
                  per burst (runner._multi_step_fn) — this is what keeps the
                  burst scan free of pool-sized copies.
      kv_scales:  (k_scales, v_scales) [L, P, KH] f32 when the pools are
                  int8 (cfg.kv_cache_dtype="int8", ops/quant.py contract):
                  reads dequantize in-kernel (or at the XLA gather), writes
                  quantize (fused prefill write / post-scan commit), and
                  the return grows to (logits, k_pages, v_pages, k_scales,
                  v_scales). kv_burst keeps its 3-tuple return (the pools
                  and scales stay read-only through the burst).

    Returns (logits[B, V] for each sequence's last valid token — or [B, T, V]
             when ``all_logits`` — and k_pages, v_pages updated; with
             ``kv_burst``: (logits, k_acc', v_acc'); with ``kv_scales``:
             (logits, k_pages, v_pages, k_scales, v_scales)).
    """
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    tp_mesh = mesh.shape.get("tp", 1) if mesh is not None else 1
    ep_mesh = mesh.shape.get("ep", 1) if mesh is not None else 1
    B, T = input_ids.shape
    x = params["embed"][input_ids].astype(cfg.dtype)  # [B, T, H]
    if sp > 1 and T > 1 and (compat.PARTIAL_MANUAL or tp_mesh == 1):
        # sequence parallelism: spread the chunk's token dim over sp so the
        # norm/QKV/MLP FLOPs parallelize too, not just attention. On the 0.4
        # toolchain this constraint makes the SPMD partitioner produce WRONG
        # activations whenever tp-SHARDED params are also present (measured
        # |dlogit| ~ |logit|max on an sp x tp mesh; exact without it, and
        # exact on sp-only meshes) — there an sp x tp chunk computes
        # sp-replicated and only attention itself parallelizes over sp.
        from jax.sharding import NamedSharding, PartitionSpec

        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, PartitionSpec("dp", "sp", None))
        )
    cos, sin = rope_cos_sin(
        jnp.maximum(positions, 0), cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    lora_scale = None if lora is None else lora["scale"][lora_ids].astype(cfg.dtype)

    post_write = cfg.kv_write_mode == "post"
    burst = kv_burst is not None
    quant = kv_scales is not None
    if quant:
        k_scales, v_scales = kv_scales
        if not post_write:
            raise ValueError("kv_cache_dtype=int8 requires kv_write_mode='post'")
        if sp > 1 or pp > 1:
            # the ring's sp sharding and the pipeline's stage relay both
            # move raw pool slices without their scales
            raise ValueError(
                "kv_cache_dtype=int8 does not compose with sp/pp meshes"
            )
    else:
        k_scales = v_scales = None
    if burst:
        if not post_write or T != 1:
            raise ValueError("kv_burst requires kv_write_mode='post' decode")
        k_acc, v_acc, burst_counts = kv_burst
        C = k_acc.shape[2]
        # pool slots >= the stale boundary hold this burst's tokens, whose
        # K/V live in the accumulator window instead (shared helper keeps
        # the XLA fallback and the kernel's masking in lockstep)
        from production_stack_tpu.ops.attention import burst_kv_positions

        kv_pos = burst_kv_positions(
            kv_lens, burst_counts + 1,
            page_table.shape[1] * k_pages.shape[2], C,
        )
    elif post_write:
        # write-after-attend: the pool is stale for this chunk, so attention
        # runs over [gathered pages at positions < chunk start] ++ [current
        # chunk K/V in-register]; per-layer K/V stack as scan outputs and one
        # batched scatter commits them after the scan (no per-layer pool
        # copies).
        kv_pos = stale_kv_positions(page_table, positions, k_pages.shape[2])

    # per-sequence aux threaded explicitly (not closed over) so the pp path
    # can slice it per microbatch; the plain path passes it whole
    aux = {
        "cos": cos, "sin": sin, "positions": positions,
        "page_table": page_table, "kv_lens": kv_lens,
        "kv_pos": kv_pos if post_write else None,
        "burst_counts": burst_counts if burst else None,
        "lora_ids": lora_ids, "lora_scale": lora_scale,
    }

    # pallas kernels stream pages straight from the STACKED pools (layer
    # index in scalar prefetch): slicing k_pages[l] per layer at the call
    # site would materialize a pool-sized copy every layer, since XLA cannot
    # fuse a dynamic-slice into a pallas_call operand (~1.5 ms/step on v5e).
    # Decode (T == 1) streams on any mesh (sharded kernel); chunked prefill
    # (T >= 16, post-write) streams single-device — multi-device prefill
    # keeps the XLA/ring path (GSPMD cannot partition a pallas_call and the
    # sp axis owns long chunks).
    single_dev = mesh is None or mesh.devices.size == 1
    # prefill kernel v2 (attn_impl="pallas_prefill", the TPU auto default /
    # "pallas_interpret" in tests): packed ragged grid + contiguous-KV DMA
    # ring — v1's page-granular (64-slot) matmuls fragmented the MXU and
    # only reached XLA parity; v2 lands N pages contiguously in VMEM and
    # folds them as ONE wide matmul (ops/pallas/prefill_attention.py).
    prefill_kernel_ok = (
        T >= 16 and single_dev and sp == 1 and kv_burst is None
        and cfg.attn_impl in ("pallas_prefill", "pallas_interpret")
    )
    stream_pools = (
        cfg.attn_impl.startswith("pallas")
        and pp == 1
        and post_write
        and (T == 1 or prefill_kernel_ok)
    )
    # fused paged-KV write: the kernel commits the chunk's K/V to the pool
    # in-kernel, the pools ride the layer scan as an aliased CARRY, and the
    # post-scan write_kv_pages_all_layers pass disappears — the chunk's KV
    # crosses HBM once instead of three times (stack write + read + scatter)
    fused_prefill = (
        prefill_kernel_ok and stream_pools and T > 1
        and getattr(cfg, "prefill_fused_kv_write", False)
    )

    def layer(x_aux, layer_in):
        if fused_prefill:
            # the pools ride the scan as CARRY: each layer's kernel writes
            # its own slice in place (aliased input->output), so the carry
            # chain is copy-free and the scan emits no stacked K/V (under
            # int8 the scales pools ride the same carry)
            if quant:
                x, aux, kp_c, vp_c, ksc_c, vsc_c = x_aux
            else:
                x, aux, kp_c, vp_c = x_aux
                ksc_c = vsc_c = None
        else:
            x, aux = x_aux
            kp_c = vp_c = ksc_c = vsc_c = None
        ksl = vsl = None  # per-layer scale slices (non-stream int8 path)
        if stream_pools:
            if burst:
                lp, li, ll, ka, va = layer_in
            else:
                lp, li, ll = layer_in  # per-layer params + layer index
            kp = vp = None
        elif quant and burst:
            lp, kp, vp, ksl, vsl, ll, ka, va = layer_in
        elif quant:
            lp, kp, vp, ksl, vsl, ll = layer_in
        elif burst:
            lp, kp, vp, ll, ka, va = layer_in
        else:
            lp, kp, vp, ll = layer_in  # per-layer params, pools, LoRA slices
        Bm, Tm = x.shape[:2]

        def proj(h, name):
            """h @ W with the batched per-sequence LoRA delta folded in."""
            y = h @ lp[name]
            if ll is not None and ("a_" + name) in ll:
                a = ll["a_" + name][aux["lora_ids"]]  # [B, in, R]
                b = ll["b_" + name][aux["lora_ids"]]  # [B, R, out]
                delta = jnp.einsum("bti,bir->btr", h, a)
                y = y + jnp.einsum("btr,bro->bto", delta, b) * (
                    aux["lora_scale"][:, None, None]
                )
            return y

        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, lp, cfg, Bm, Tm, aux["cos"], aux["sin"], proj)
        if burst:
            # append the current token into the burst window at slot
            # ``counts`` (entries 0..counts-1 hold earlier burst tokens);
            # the window, not the pool, carries this burst's K/V
            rows = jnp.arange(Bm, dtype=jnp.int32)
            cnt = aux["burst_counts"]
            kwin = ka.at[rows, cnt].set(k[:, 0].astype(ka.dtype))
            vwin = va.at[rows, cnt].set(v[:, 0].astype(va.dtype))
        if not post_write:
            kp, vp = write_kv_pages(
                kp, vp, k.astype(kp.dtype), v.astype(vp.dtype),
                aux["page_table"], aux["positions"],
            )
        if Tm == 1 and cfg.attn_impl.startswith("pallas"):
            # decode: stream pages HBM->VMEM, no gather materialization; in
            # post mode the current token's K/V fold in from registers. On a
            # multi-device dp x tp mesh the kernel runs per shard via
            # shard_map (GSPMD cannot partition a pallas_call).
            from production_stack_tpu.ops.pallas.paged_attention import (
                ragged_paged_attention_decode,
                ragged_paged_attention_decode_sharded,
            )

            # the in-register window stays fp under int8 pools — it is the
            # quantizer's INPUT, committed by the post-scan quant scatter
            cur_dt = cfg.dtype if quant else k_pages.dtype
            if burst:
                cur_kw = dict(
                    k_cur=kwin, v_cur=vwin,
                    cur_lens=aux["burst_counts"] + 1,
                )
            elif post_write:
                cur_kw = dict(
                    k_cur=k[:, 0].astype(cur_dt),
                    v_cur=v[:, 0].astype(cur_dt),
                )
            else:
                cur_kw = dict(k_cur=None, v_cur=None)
            pallas_kw = dict(
                window=cfg.sliding_window,
                interpret=cfg.attn_impl == "pallas_interpret",
                pages_per_block=cfg.decode_pages_per_block or None,
                prefetch_pages=cfg.decode_prefetch_pages or None,
                **cur_kw,
            )
            if stream_pools:
                pool_args = (k_pages, v_pages)
                pallas_kw["layer"] = li
                if quant:
                    pallas_kw["k_scales"] = k_scales
                    pallas_kw["v_scales"] = v_scales
            else:
                pool_args = (kp, vp)
                if quant:
                    pallas_kw["k_scales"] = ksl
                    pallas_kw["v_scales"] = vsl
            # under pp the kernel runs INSIDE the pipeline's manual region.
            # With partial-manual shard_map that nests (the sharded call maps
            # the remaining axes); without it (old jax) the pipeline region
            # is already full-manual — every operand is a stage-local,
            # tp-replicated shard — so the plain kernel on local data IS the
            # correct per-shard program and nesting would be an error.
            if mesh is not None and mesh.devices.size > 1 and (
                pp == 1 or compat.PARTIAL_MANUAL
            ):
                attn = ragged_paged_attention_decode_sharded(
                    mesh, q[:, 0], *pool_args,
                    aux["page_table"], aux["kv_lens"],
                    **pallas_kw,
                )[:, None]
            else:
                attn = ragged_paged_attention_decode(
                    q[:, 0], *pool_args, aux["page_table"], aux["kv_lens"],
                    **pallas_kw,
                )[:, None]
        elif (
            Tm > 1
            and cfg.attn_impl.startswith("pallas")
            and stream_pools
            and not burst
        ):
            # chunked prefill: pallas flash kernel streams pages HBM->VMEM
            # (no [B, S, KH, D] pool gather) and folds the chunk's own K/V
            # in-register — the XLA scan ran at <20% MFU at 16k context
            # (ops/pallas/prefill_attention.py)
            from production_stack_tpu.ops.pallas.prefill_attention import (
                ragged_paged_attention_prefill,
            )

            chunk_dt = cfg.dtype if quant else k_pages.dtype
            kernel_kw = dict(
                window=cfg.sliding_window,
                interpret=cfg.attn_impl == "pallas_interpret",
                pages_per_block=getattr(cfg, "prefill_pages_per_block", 0)
                or None,
                prefetch_pages=getattr(cfg, "prefill_prefetch_pages", 0)
                or None,
                layer=li,
            )
            if quant:
                kernel_kw["k_scales"] = ksc_c if fused_prefill else k_scales
                kernel_kw["v_scales"] = vsc_c if fused_prefill else v_scales
            kernel_args = (
                q,
                kp_c if fused_prefill else k_pages,
                vp_c if fused_prefill else v_pages,
                aux["page_table"], aux["positions"], aux["kv_lens"],
                k.astype(chunk_dt), v.astype(chunk_dt),
                jnp.sum(aux["positions"] >= 0, axis=1).astype(jnp.int32),
            )
            if fused_prefill and quant:
                attn, kp_c, vp_c, ksc_c, vsc_c = ragged_paged_attention_prefill(
                    *kernel_args, fused_write=True, **kernel_kw
                )
            elif fused_prefill:
                attn, kp_c, vp_c = ragged_paged_attention_prefill(
                    *kernel_args, fused_write=True, **kernel_kw
                )
            else:
                attn = ragged_paged_attention_prefill(
                    *kernel_args, **kernel_kw
                )
        else:
            if quant:
                from production_stack_tpu.ops.quant import (
                    gather_kv_pages_quant,
                )

                kc, vc = gather_kv_pages_quant(
                    kp, vp, ksl, vsl, aux["page_table"], dtype=cfg.dtype
                )
            else:
                kc, vc = gather_kv_pages(kp, vp, aux["page_table"])
            if burst:
                kc = jnp.concatenate([kc, kwin.astype(kc.dtype)], axis=1)
                vc = jnp.concatenate([vc, vwin.astype(vc.dtype)], axis=1)
            elif post_write:
                kc = jnp.concatenate([kc, k.astype(kc.dtype)], axis=1)
                vc = jnp.concatenate([vc, v.astype(vc.dtype)], axis=1)
            # On old jax (no partial-manual shard_map: compat.PARTIAL_MANUAL
            # False) the ring's full-manual region nested inside this layer
            # scan MISCOMPILES whenever the mesh also has a >1 axis that is
            # mapped but unmentioned in the specs — measured |dlogit| ~
            # |logit|max on sp x tp while the same ring is exact standalone,
            # sp-only, and under every reduced repro. The widened ring maps
            # dp/tp explicitly (ring_attention_serving), but ep has no
            # natural attention axis to map, so an ep > 1 mesh carries the
            # same hazard as unmapped tp did; the GSPMD flash path below is
            # exact there, so sp x tp-or-ep prefill takes it (trading ring's
            # sequence-axis sharding for correctness on that toolchain).
            # Modern jax keeps the ring via partial manual.
            # pp has no attention axis either, so the widened ring would
            # refuse it (unmappable) — require pp == 1 so the fallback is
            # the flash path, not a trace-time ValueError
            ring_ok = compat.PARTIAL_MANUAL or (
                tp_mesh == 1 and ep_mesh == 1 and pp == 1
            )
            if sp > 1 and Tm > 1 and cfg.sliding_window is None and ring_ok:
                # sequence-parallel prefill: ring attention over the sp axis
                # (KV blocks rotate via ppermute while queries stay local)
                from production_stack_tpu.parallel.ring_attention import (
                    ring_attention_serving,
                )

                if post_write:
                    # stale_kv_positions already covers pool slots + chunk
                    kvp = aux["kv_pos"]
                else:
                    S = kc.shape[1]
                    kvp = jnp.broadcast_to(
                        jnp.arange(S, dtype=jnp.int32), (Bm, S)
                    )
                attn = ring_attention_serving(
                    mesh, q, kc, vc, aux["positions"], kvp
                )
            else:
                attn = flash_attention(
                    q, kc, vc, q_positions=aux["positions"],
                    kv_lens=aux["kv_lens"],
                    window=cfg.sliding_window,
                    kv_positions=aux["kv_pos"] if post_write else None,
                )
        x = x + proj(attn.reshape(Bm, Tm, -1), "wo")
        x = _mlp_residual(x, lp, cfg, proj)
        if fused_prefill:
            # the kernel already committed this layer's K/V to the pool
            if quant:
                return (x, aux, kp_c, vp_c, ksc_c, vsc_c), None
            return (x, aux, kp_c, vp_c), None
        if burst:
            out_kv = (kwin, vwin)  # stacked by the scan -> [L, B, C, KH, D]
        elif post_write:
            # int8 pools: stack fp — the post-scan commit is the quantizer
            store_dt = cfg.dtype if quant else k_pages.dtype
            out_kv = (k.astype(store_dt), v.astype(store_dt))
        else:
            out_kv = (kp, vp)
        return (x, aux), out_kv

    lora_layers = None if lora is None else lora["layers"]
    if stream_pools:
        scan_xs = (
            params["layers"],
            jnp.arange(cfg.num_layers, dtype=jnp.int32),
            lora_layers,
        )
    elif quant:
        # per-layer scale slices ride the scan next to the pool slices
        scan_xs = (
            params["layers"], k_pages, v_pages, k_scales, v_scales,
            lora_layers,
        )
    else:
        scan_xs = (params["layers"], k_pages, v_pages, lora_layers)
    if burst:
        if pp > 1:
            raise ValueError("kv_burst does not compose with pipeline stages")
        (x, _), (k_acc, v_acc) = lax.scan(
            layer, (x, aux), scan_xs + (kv_burst[0], kv_burst[1])
        )
        # NO pool write: the caller commits the accumulated windows once per
        # burst — the pools stay loop constants through the burst scan
    elif pp > 1:
        if not post_write:
            raise ValueError("pipeline parallelism requires kv_write_mode='post'")
        from production_stack_tpu.parallel.pipeline import serving_layer_pipeline

        x, (k_new, v_new) = serving_layer_pipeline(mesh, layer, x, aux, scan_xs)
        k_pages, v_pages = write_kv_pages_all_layers(
            k_pages, v_pages, k_new, v_new, page_table, positions
        )
    elif fused_prefill and quant:
        # no post-scan scatter: every layer's kernel wrote its pool + scale
        # slices in place
        (x, _, k_pages, v_pages, k_scales, v_scales), _ = lax.scan(
            layer, (x, aux, k_pages, v_pages, k_scales, v_scales), scan_xs
        )
    elif fused_prefill:
        # no post-scan scatter: every layer's kernel wrote its pool slice
        (x, _, k_pages, v_pages), _ = lax.scan(
            layer, (x, aux, k_pages, v_pages), scan_xs
        )
    elif post_write and quant:
        (x, _), (k_new, v_new) = lax.scan(layer, (x, aux), scan_xs)
        from production_stack_tpu.ops.quant import (
            write_kv_pages_all_layers_quant,
        )

        k_pages, v_pages, k_scales, v_scales = write_kv_pages_all_layers_quant(
            k_pages, v_pages, k_scales, v_scales, k_new, v_new,
            page_table, positions,
        )
    elif post_write:
        (x, _), (k_new, v_new) = lax.scan(layer, (x, aux), scan_xs)
        k_pages, v_pages = write_kv_pages_all_layers(
            k_pages, v_pages, k_new, v_new, page_table, positions
        )
    else:
        (x, _), (k_pages, v_pages) = lax.scan(layer, (x, aux), scan_xs)

    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    if all_logits:
        # speculative verify: T is small (1 + draft length), so [B, T, V] fits
        if quant:
            return (
                (x @ head).astype(jnp.float32),
                k_pages, v_pages, k_scales, v_scales,
            )
        return (x @ head).astype(jnp.float32), k_pages, v_pages
    # Select each sequence's last valid token before the vocab projection so the
    # logits tensor is [B, V], not [B, T, V] (a 2 GB save at V=128k, T=1k).
    last_idx = jnp.maximum(jnp.sum(positions >= 0, axis=1) - 1, 0)  # [B]
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]  # [B, H]
    logits = (x_last @ head).astype(jnp.float32)
    if burst:
        return logits, k_acc, v_acc
    if quant:
        return logits, k_pages, v_pages, k_scales, v_scales
    return logits, k_pages, v_pages
