"""OPT family (facebook/opt-125m … opt-66b) as pure functional JAX.

Same TPU-first structure as models/llama.py (layer-stacked weights under one
``lax.scan``, paged KV, -1-position padding), with the OPT architectural
differences: learned positional embeddings (HF offset of 2), pre-LayerNorm
blocks with biases everywhere, ReLU MLP, no RoPE, no GQA.

Reference parity: the reference stack's CPU smoke test serves
``facebook/opt-125m`` (tutorials/assets/values-01-minimal-example.yaml and
.github/workflows/functionality-helm-chart.yml in /root/reference); this module
makes that same model a first-class citizen of the TPU engine.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from production_stack_tpu.ops.attention import (
    flash_attention,
    gather_kv_pages,
    stale_kv_positions,
    write_kv_pages,
    write_kv_pages_all_layers,
)
from production_stack_tpu.ops.norms import layer_norm

# HF OPT reserves the first 2 position-embedding rows (legacy padding offset).
POS_OFFSET = 2


@dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    layer_norm_eps: float = 1e-5
    max_model_len: int = 2048
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"  # same contract as LlamaConfig.attn_impl
    kv_write_mode: str = "post"  # same contract as LlamaConfig.kv_write_mode
    decode_pages_per_block: int = 0  # same contract as LlamaConfig
    decode_prefetch_pages: int = 0
    prefill_pages_per_block: int = 0  # same contract as LlamaConfig
    prefill_prefetch_pages: int = 0
    # accepted for config-threading uniformity; OPT's layer scan carries
    # pools as per-layer xs slices (no stacked-pool streaming), so its
    # prefill kernel path keeps the post-scan scatter regardless
    prefill_fused_kv_write: bool = True

    # uniform accessors used by the runner/engine (OPT has no GQA)
    @property
    def num_kv_heads(self) -> int:
        return self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def tie_word_embeddings(self) -> bool:
        return True

    @property
    def sliding_window(self):
        return None

    @staticmethod
    def from_hf_config(cfg: dict) -> "OPTConfig":
        """Build from a HuggingFace `config.json` (OPTForCausalLM)."""
        if cfg.get("word_embed_proj_dim", cfg["hidden_size"]) != cfg["hidden_size"]:
            raise NotImplementedError("OPT word_embed_proj_dim != hidden_size")
        return OPTConfig(
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["ffn_dim"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            max_model_len=cfg.get("max_position_embeddings", 2048),
        )


PRESETS: dict[str, OPTConfig] = {
    "opt-125m": OPTConfig(),
    "opt-debug": OPTConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        max_model_len=256,
    ),
}


def init_params(cfg: OPTConfig, key: jax.Array) -> dict:
    """Random-normal initialized parameter tree (layer-stacked)."""
    k_embed, k_pos, k_layers = jax.random.split(key, 3)
    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 6)
    scale = H**-0.5
    return {
        "embed": normal(k_embed, (cfg.vocab_size, H), scale),
        "pos_embed": normal(k_pos, (cfg.max_model_len + POS_OFFSET, H), scale),
        "layers": {
            "attn_norm_w": jnp.ones((L, H), cfg.dtype),
            "attn_norm_b": jnp.zeros((L, H), cfg.dtype),
            "wq": normal(ks[0], (L, H, H), scale),
            "bq": jnp.zeros((L, H), cfg.dtype),
            "wk": normal(ks[1], (L, H, H), scale),
            "bk": jnp.zeros((L, H), cfg.dtype),
            "wv": normal(ks[2], (L, H, H), scale),
            "bv": jnp.zeros((L, H), cfg.dtype),
            "wo": normal(ks[3], (L, H, H), scale),
            "bo": jnp.zeros((L, H), cfg.dtype),
            "mlp_norm_w": jnp.ones((L, H), cfg.dtype),
            "mlp_norm_b": jnp.zeros((L, H), cfg.dtype),
            "fc1": normal(ks[4], (L, H, I), scale),
            "fc1_b": jnp.zeros((L, I), cfg.dtype),
            "fc2": normal(ks[5], (L, I, H), I**-0.5),
            "fc2_b": jnp.zeros((L, H), cfg.dtype),
        },
        "final_norm_w": jnp.ones((H,), cfg.dtype),
        "final_norm_b": jnp.zeros((H,), cfg.dtype),
    }


def init_kv_pages(
    cfg: OPTConfig, num_pages: int, page_size: int, dtype=None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Layer-stacked page pools: [L, num_pages, page_size, NH, D]."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def forward(
    params: dict,
    cfg: OPTConfig,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lens: jnp.ndarray,
    all_logits: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One forward step (prefill chunk or decode) with paged KV.

    Same contract as models/llama.py `forward` (returns last-valid-token
    logits [B, V] and the updated page pools).
    """
    B, T = input_ids.shape
    NH, D = cfg.num_heads, cfg.head_dim
    pos_ids = jnp.maximum(positions, 0) + POS_OFFSET
    x = (params["embed"][input_ids] + params["pos_embed"][pos_ids]).astype(cfg.dtype)

    post_write = cfg.kv_write_mode == "post"
    if post_write:
        # write-after-attend (see models/llama.py): stale pool + in-register
        # chunk K/V, one batched all-layer scatter after the scan
        kv_pos = stale_kv_positions(page_table, positions, k_pages.shape[2])

    def layer(x, layer_in):
        lp, kp, vp = layer_in
        h = layer_norm(x, lp["attn_norm_w"], lp["attn_norm_b"], cfg.layer_norm_eps)
        q = (h @ lp["wq"] + lp["bq"]).reshape(B, T, NH, D)
        k = (h @ lp["wk"] + lp["bk"]).reshape(B, T, NH, D)
        v = (h @ lp["wv"] + lp["bv"]).reshape(B, T, NH, D)
        if not post_write:
            kp, vp = write_kv_pages(
                kp, vp, k.astype(kp.dtype), v.astype(vp.dtype), page_table, positions
            )
        if T == 1 and cfg.attn_impl.startswith("pallas"):
            from production_stack_tpu.ops.pallas.paged_attention import (
                ragged_paged_attention_decode,
            )

            attn = ragged_paged_attention_decode(
                q[:, 0], kp, vp, page_table, kv_lens,
                interpret=cfg.attn_impl == "pallas_interpret",
                k_cur=k[:, 0].astype(kp.dtype) if post_write else None,
                v_cur=v[:, 0].astype(vp.dtype) if post_write else None,
                pages_per_block=cfg.decode_pages_per_block or None,
                prefetch_pages=cfg.decode_prefetch_pages or None,
            )[:, None]
        elif (
            T >= 16 and post_write
            and cfg.attn_impl in ("pallas_prefill", "pallas_interpret")
        ):
            # chunked prefill via kernel v2 (see models/llama.py); OPT's
            # scan carries per-layer pool slices, so the post-scan scatter
            # stays and fused_write is not used here
            from production_stack_tpu.ops.pallas.prefill_attention import (
                ragged_paged_attention_prefill,
            )

            attn = ragged_paged_attention_prefill(
                q, kp, vp, page_table, positions, kv_lens,
                k.astype(kp.dtype), v.astype(vp.dtype),
                jnp.sum(positions >= 0, axis=1).astype(jnp.int32),
                interpret=cfg.attn_impl == "pallas_interpret",
                pages_per_block=cfg.prefill_pages_per_block or None,
                prefetch_pages=cfg.prefill_prefetch_pages or None,
            )
        elif post_write:
            kc, vc = gather_kv_pages(kp, vp, page_table)
            kc = jnp.concatenate([kc, k.astype(kc.dtype)], axis=1)
            vc = jnp.concatenate([vc, v.astype(vc.dtype)], axis=1)
            attn = flash_attention(
                q, kc, vc, q_positions=positions, kv_lens=kv_lens,
                kv_positions=kv_pos,
            )
        else:
            kc, vc = gather_kv_pages(kp, vp, page_table)
            attn = flash_attention(q, kc, vc, q_positions=positions, kv_lens=kv_lens)
        x = x + attn.reshape(B, T, -1) @ lp["wo"] + lp["bo"]
        h = layer_norm(x, lp["mlp_norm_w"], lp["mlp_norm_b"], cfg.layer_norm_eps)
        x = x + jax.nn.relu(h @ lp["fc1"] + lp["fc1_b"]) @ lp["fc2"] + lp["fc2_b"]
        out_kv = (
            (k.astype(kp.dtype), v.astype(vp.dtype)) if post_write else (kp, vp)
        )
        return x, out_kv

    if post_write:
        x, (k_new, v_new) = lax.scan(layer, x, (params["layers"], k_pages, v_pages))
        k_pages, v_pages = write_kv_pages_all_layers(
            k_pages, v_pages, k_new, v_new, page_table, positions
        )
    else:
        x, (k_pages, v_pages) = lax.scan(layer, x, (params["layers"], k_pages, v_pages))

    x = layer_norm(x, params["final_norm_w"], params["final_norm_b"], cfg.layer_norm_eps)
    if all_logits:  # speculative verify scores every position
        return (x @ params["embed"].T).astype(jnp.float32), k_pages, v_pages
    last_idx = jnp.maximum(jnp.sum(positions >= 0, axis=1) - 1, 0)
    x_last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    logits = (x_last @ params["embed"].T).astype(jnp.float32)
    return logits, k_pages, v_pages
