"""Model registry.

Every model module exposes the same duck-typed interface consumed by
engine/runner.py and engine/model_loader.py:

- ``Config`` dataclass (``from_hf_config``, ``attn_impl``, ``num_layers``,
  ``num_kv_heads``, ``head_dim``, ``max_model_len``, ``dtype``)
- ``PRESETS: dict[str, Config]``
- ``init_params(cfg, key)`` / ``init_kv_pages(cfg, num_pages, page_size)``
- ``forward(params, cfg, input_ids, positions, k_pages, v_pages, page_table,
  kv_lens) -> (logits, k_pages, v_pages)``

Sharding specs are name-based (parallel/shardings.py) so new families only
need to reuse the leaf-name vocabulary or extend the spec tables.
"""

from __future__ import annotations

from production_stack_tpu.models import gemma2, llama, opt

#: module search order for preset names and HF architectures
MODULES = (llama, opt, gemma2)

_ARCH_TO_MODULE = {
    "LlamaForCausalLM": llama,
    "MistralForCausalLM": llama,
    "Qwen2ForCausalLM": llama,
    "MixtralForCausalLM": llama,
    "OPTForCausalLM": opt,
    "Gemma2ForCausalLM": gemma2,
}


def module_for_arch(arch: str):
    """Map a HuggingFace `architectures[0]` string to a model module."""
    try:
        return _ARCH_TO_MODULE[arch]
    except KeyError:
        raise ValueError(
            f"unsupported architecture {arch!r}; supported: {sorted(_ARCH_TO_MODULE)}"
        ) from None


def module_for_config(cfg):
    """Map a model config instance back to its module."""
    if isinstance(cfg, llama.LlamaConfig):
        return llama
    if isinstance(cfg, opt.OPTConfig):
        return opt
    if isinstance(cfg, gemma2.Gemma2Config):
        return gemma2
    raise ValueError(f"unknown model config type {type(cfg).__name__}")


def find_preset(name: str):
    """Return (module, config) for a preset name, or None."""
    for mod in MODULES:
        if name in mod.PRESETS:
            return mod, mod.PRESETS[name]
    return None
