"""Gemma-2 family (gemma-2-2b/9b/27b) as pure functional JAX.

Same TPU-first structure as models/llama.py (layer-stacked weights under one
``lax.scan``, paged KV pools, -1-position padding), with the Gemma-2
architectural differences:

- interleaved attention: even layers use a sliding window, odd layers are
  global. The per-layer window rides the decoder scan as an ``xs`` array, so
  one traced layer still serves both kinds (global layers get a window wider
  than any context — the comparison folds into the existing mask math).
- logit softcapping: ``cap * tanh(x / cap)`` on attention scores (50.0) and
  final logits (30.0).
- GeGLU MLP (tanh-approximate GELU on the gate path).
- sandwich norms: RMSNorm before *and after* each attention/MLP block, with
  Gemma's zero-centered ``(1 + w)`` weight parameterization.
- embeddings scaled by sqrt(hidden); attention scaled by
  ``query_pre_attn_scalar**-0.5`` instead of ``head_dim**-0.5``.

Reference parity: the reference stack serves any vLLM-supported model through
its engine contract (SURVEY.md §1 L4); Gemma-2 is a headline open-weights
family a reference user would expect to deploy unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from production_stack_tpu.ops.attention import (
    flash_attention,
    gather_kv_pages,
    stale_kv_positions,
    write_kv_pages,
    write_kv_pages_all_layers,
)


@dataclass(frozen=True)
class Gemma2Config:
    vocab_size: int = 256000
    hidden_size: int = 3584
    intermediate_size: int = 14336
    num_layers: int = 42
    num_heads: int = 16
    num_kv_heads: int = 8
    head_dim: int = 256
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    max_model_len: int = 8192
    query_pre_attn_scalar: float = 256.0
    attn_logit_softcap: Optional[float] = 50.0
    final_logit_softcap: Optional[float] = 30.0
    sliding_window: int = 4096        # even layers; odd layers are global
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"           # same contract as LlamaConfig.attn_impl
    kv_write_mode: str = "post"       # same contract as LlamaConfig.kv_write_mode
    decode_pages_per_block: int = 0   # same contract as LlamaConfig
    decode_prefetch_pages: int = 0
    prefill_pages_per_block: int = 0  # same contract as LlamaConfig
    prefill_prefetch_pages: int = 0
    prefill_fused_kv_write: bool = True
    # KV cache dtype (same contract as LlamaConfig.kv_cache_dtype): "int8"
    # stores quantized pages + per-page per-kv-head scales (ops/quant.py);
    # ModelRunner builds the scales pools and threads them as ``kv_scales``
    kv_cache_dtype: str = "auto"

    @property
    def tie_word_embeddings(self) -> bool:
        return True  # Gemma always ties the LM head to the embedding

    @staticmethod
    def from_hf_config(cfg: dict) -> "Gemma2Config":
        hidden = cfg["hidden_size"]
        heads = cfg["num_attention_heads"]
        return Gemma2Config(
            vocab_size=cfg["vocab_size"],
            hidden_size=hidden,
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=heads,
            num_kv_heads=cfg.get("num_key_value_heads", heads),
            head_dim=cfg.get("head_dim") or hidden // heads,
            rope_theta=cfg.get("rope_theta", 10000.0),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-6),
            max_model_len=cfg.get("max_position_embeddings", 8192),
            query_pre_attn_scalar=cfg.get("query_pre_attn_scalar", 256.0),
            attn_logit_softcap=cfg.get("attn_logit_softcapping", 50.0),
            final_logit_softcap=cfg.get("final_logit_softcapping", 30.0),
            sliding_window=cfg.get("sliding_window", 4096),
        )


PRESETS: dict[str, Gemma2Config] = {
    "gemma-2-9b": Gemma2Config(),
    "gemma-2-2b": Gemma2Config(
        hidden_size=2304,
        intermediate_size=9216,
        num_layers=26,
        num_heads=8,
        num_kv_heads=4,
        query_pre_attn_scalar=256.0,
    ),
    "gemma2-debug": Gemma2Config(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,            # layer 0 sliding, layer 1 global
        num_heads=4,
        num_kv_heads=2,
        head_dim=32,
        query_pre_attn_scalar=32.0,
        sliding_window=8,
        max_model_len=256,
    ),
}


def init_params(cfg: Gemma2Config, key: jax.Array) -> dict:
    """Random-normal parameter tree (layer-stacked). Norm weights start at
    zero — Gemma's RMSNorm multiplies by (1 + w)."""
    k_embed, k_layers = jax.random.split(key)
    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    NH, KH, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    ks = jax.random.split(k_layers, 7)
    scale = H**-0.5
    layers = {
        "attn_norm": jnp.zeros((L, H), cfg.dtype),
        "post_attn_norm": jnp.zeros((L, H), cfg.dtype),
        "mlp_norm": jnp.zeros((L, H), cfg.dtype),
        "post_mlp_norm": jnp.zeros((L, H), cfg.dtype),
        "wq": normal(ks[0], (L, H, NH * D), scale),
        "wk": normal(ks[1], (L, H, KH * D), scale),
        "wv": normal(ks[2], (L, H, KH * D), scale),
        "wo": normal(ks[3], (L, NH * D, H), (NH * D) ** -0.5),
        "w_gate": normal(ks[4], (L, H, I), scale),
        "w_up": normal(ks[5], (L, H, I), scale),
        "w_down": normal(ks[6], (L, I, H), I**-0.5),
    }
    return {
        "embed": normal(k_embed, (cfg.vocab_size, H), scale),
        "layers": layers,
        "final_norm": jnp.zeros((H,), cfg.dtype),
    }


def init_kv_pages(
    cfg: Gemma2Config, num_pages: int, page_size: int, dtype=None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Layer-stacked page pools: [L, num_pages, page_size, KH, D]."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def _rms_norm_1p(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    """Gemma RMSNorm: zero-centered weight, stats and (1 + w) in fp32."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps) * (1.0 + w.astype(jnp.float32))).astype(dtype)


def _layer_windows(cfg: Gemma2Config) -> jnp.ndarray:
    """Per-layer window sizes for the decoder scan: even layers slide, odd
    layers see everything (a window wider than any position is a no-op)."""
    full = cfg.max_model_len + 1
    return jnp.asarray(
        [cfg.sliding_window if i % 2 == 0 else full for i in range(cfg.num_layers)],
        jnp.int32,
    )


# dp/tp only: ring-attention prefill and pipeline stages are llama-family
# features; the runner gates sp/pp on this declaration
MESH_AXES = ("dp", "tp")


def forward(
    params: dict,
    cfg: Gemma2Config,
    input_ids: jnp.ndarray,
    positions: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lens: jnp.ndarray,
    all_logits: bool = False,
    kv_burst=None,
    mesh=None,
    kv_scales=None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One forward step (prefill chunk or decode) with paged KV.

    Same contract as models/llama.py:forward (including ``kv_burst``
    deferred-scatter decode); returns (logits[B, V] for each sequence's last
    valid token — [B, T, V] when ``all_logits``, used by speculative verify
    — and updated k_pages, v_pages; with ``kv_burst``: the accumulators).
    """
    from production_stack_tpu.ops.rope import apply_rope, rope_cos_sin

    B, T = input_ids.shape
    x = params["embed"][input_ids].astype(cfg.dtype)
    x = x * jnp.asarray(cfg.hidden_size**0.5, cfg.dtype)  # Gemma embed scaling
    cos, sin = rope_cos_sin(jnp.maximum(positions, 0), cfg.head_dim, cfg.rope_theta)
    sm_scale = cfg.query_pre_attn_scalar**-0.5
    eps = cfg.rms_norm_eps

    post_write = cfg.kv_write_mode == "post"
    burst = kv_burst is not None
    quant = kv_scales is not None
    if quant:
        k_scales, v_scales = kv_scales
        if not post_write:
            raise ValueError("kv_cache_dtype=int8 requires kv_write_mode='post'")
    else:
        k_scales = v_scales = None
    if burst:
        if not post_write or T != 1:
            raise ValueError("kv_burst requires kv_write_mode='post' decode")
        k_acc0, v_acc0, burst_counts = kv_burst
        C = k_acc0.shape[2]
        from production_stack_tpu.ops.attention import burst_kv_positions

        kv_pos = burst_kv_positions(
            kv_lens, burst_counts + 1,
            page_table.shape[1] * k_pages.shape[2], C,
        )
    elif post_write:
        # write-after-attend (see models/llama.py): stale pool + in-register
        # chunk K/V, one batched all-layer scatter after the scan
        kv_pos = stale_kv_positions(page_table, positions, k_pages.shape[2])

    # pallas decode streams straight from the stacked pools via a layer
    # index (see models/llama.py stream_pools); prefill kernel v2 does the
    # same for chunks — the per-layer window rides the scan as a traced
    # scalar-prefetch operand, so Gemma's interleaved local/global layers
    # each stream only their live page range
    single_dev = mesh is None or mesh.devices.size == 1
    prefill_kernel_ok = (
        T >= 16 and single_dev and kv_burst is None and post_write
        and cfg.attn_impl in ("pallas_prefill", "pallas_interpret")
    )
    stream_pools = (
        cfg.attn_impl.startswith("pallas") and post_write
        and (T == 1 or prefill_kernel_ok)
    )
    fused_prefill = (
        prefill_kernel_ok and stream_pools and T > 1
        and cfg.prefill_fused_kv_write
    )

    def layer(x_carry, layer_in):
        if fused_prefill:
            if quant:  # scales pools ride the same aliased carry
                x, kp_c, vp_c, ksc_c, vsc_c = x_carry
            else:
                x, kp_c, vp_c = x_carry  # pools ride the scan as aliased carry
                ksc_c = vsc_c = None
        else:
            x = x_carry
            kp_c = vp_c = ksc_c = vsc_c = None
        ksl = vsl = None  # per-layer scale slices (non-stream int8 path)
        if stream_pools:
            if burst:
                lp, li, window, ka, va = layer_in
            else:
                lp, li, window = layer_in
            kp = vp = None
        elif quant and burst:
            lp, kp, vp, ksl, vsl, window, ka, va = layer_in
        elif quant:
            lp, kp, vp, ksl, vsl, window = layer_in
        elif burst:
            lp, kp, vp, window, ka, va = layer_in
        else:
            lp, kp, vp, window = layer_in

        h = _rms_norm_1p(x, lp["attn_norm"], eps)
        q = (h @ lp["wq"]).reshape(B, T, cfg.num_heads, cfg.head_dim)
        k = (h @ lp["wk"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
        q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
        # in-register window / chunk K/V stay fp under int8 pools — they
        # feed the quantizer (post-scan commit or fused in-kernel write)
        pool_dt = cfg.dtype if quant else k_pages.dtype
        if burst:
            rows = jnp.arange(B, dtype=jnp.int32)
            cnt = burst_counts
            kwin = ka.at[rows, cnt].set(k[:, 0].astype(pool_dt))
            vwin = va.at[rows, cnt].set(v[:, 0].astype(pool_dt))
        if not post_write:
            kp, vp = write_kv_pages(
                kp, vp, k.astype(kp.dtype), v.astype(vp.dtype), page_table, positions
            )
        if T == 1 and cfg.attn_impl.startswith("pallas"):
            # decode: page-streaming kernel; the per-layer window rides the
            # scan as a traced scalar-prefetch operand
            from production_stack_tpu.ops.pallas.paged_attention import (
                ragged_paged_attention_decode,
                ragged_paged_attention_decode_sharded,
            )

            if burst:
                cur_kw = dict(
                    k_cur=kwin, v_cur=vwin, cur_lens=burst_counts + 1
                )
            elif post_write:
                cur_kw = dict(
                    k_cur=k[:, 0].astype(pool_dt),
                    v_cur=v[:, 0].astype(pool_dt),
                )
            else:
                cur_kw = dict(k_cur=None, v_cur=None)
            if stream_pools:
                pool_args, layer_kw = (k_pages, v_pages), {"layer": li}
                if quant:
                    layer_kw.update(k_scales=k_scales, v_scales=v_scales)
            else:
                pool_args, layer_kw = (kp, vp), {}
                if quant:
                    layer_kw.update(k_scales=ksl, v_scales=vsl)
            common = dict(
                window=window, sm_scale=sm_scale,
                logit_softcap=cfg.attn_logit_softcap,
                interpret=cfg.attn_impl == "pallas_interpret",
                pages_per_block=cfg.decode_pages_per_block or None,
                prefetch_pages=cfg.decode_prefetch_pages or None,
                **cur_kw, **layer_kw,
            )
            if mesh is not None and mesh.devices.size > 1:
                attn = ragged_paged_attention_decode_sharded(
                    mesh, q[:, 0], *pool_args, page_table, kv_lens, **common
                )[:, None]
            else:
                attn = ragged_paged_attention_decode(
                    q[:, 0], *pool_args, page_table, kv_lens, **common
                )[:, None]
        elif prefill_kernel_ok:
            # chunked prefill: ragged packed grid + contiguous-KV DMA ring
            # (+ fused paged-KV write when the pools ride the carry)
            from production_stack_tpu.ops.pallas.prefill_attention import (
                ragged_paged_attention_prefill,
            )

            kernel_kw = dict(
                window=window, sm_scale=sm_scale,
                logit_softcap=cfg.attn_logit_softcap,
                interpret=cfg.attn_impl == "pallas_interpret",
                pages_per_block=cfg.prefill_pages_per_block or None,
                prefetch_pages=cfg.prefill_prefetch_pages or None,
                layer=li,
            )
            if quant:
                kernel_kw["k_scales"] = ksc_c if fused_prefill else k_scales
                kernel_kw["v_scales"] = vsc_c if fused_prefill else v_scales
            kernel_args = (
                q,
                kp_c if fused_prefill else k_pages,
                vp_c if fused_prefill else v_pages,
                page_table, positions, kv_lens,
                k.astype(pool_dt), v.astype(pool_dt),
                jnp.sum(positions >= 0, axis=1).astype(jnp.int32),
            )
            if fused_prefill and quant:
                attn, kp_c, vp_c, ksc_c, vsc_c = ragged_paged_attention_prefill(
                    *kernel_args, fused_write=True, **kernel_kw
                )
            elif fused_prefill:
                attn, kp_c, vp_c = ragged_paged_attention_prefill(
                    *kernel_args, fused_write=True, **kernel_kw
                )
            else:
                attn = ragged_paged_attention_prefill(
                    *kernel_args, **kernel_kw
                )
        elif post_write:
            if quant:
                from production_stack_tpu.ops.quant import (
                    gather_kv_pages_quant,
                )

                kc, vc = gather_kv_pages_quant(
                    kp, vp, ksl, vsl, page_table, dtype=cfg.dtype
                )
            else:
                kc, vc = gather_kv_pages(kp, vp, page_table)
            if burst:
                kc = jnp.concatenate([kc, kwin.astype(kc.dtype)], axis=1)
                vc = jnp.concatenate([vc, vwin.astype(vc.dtype)], axis=1)
            else:
                kc = jnp.concatenate([kc, k.astype(kc.dtype)], axis=1)
                vc = jnp.concatenate([vc, v.astype(vc.dtype)], axis=1)
            attn = flash_attention(
                q, kc, vc, q_positions=positions, kv_lens=kv_lens,
                sm_scale=sm_scale, window=window,
                logit_softcap=cfg.attn_logit_softcap, kv_positions=kv_pos,
            )
        else:
            kc, vc = gather_kv_pages(kp, vp, page_table)
            attn = flash_attention(
                q, kc, vc, q_positions=positions, kv_lens=kv_lens,
                sm_scale=sm_scale, window=window,
                logit_softcap=cfg.attn_logit_softcap,
            )
        attn = (attn.reshape(B, T, -1)) @ lp["wo"]
        x = x + _rms_norm_1p(attn, lp["post_attn_norm"], eps)

        h = _rms_norm_1p(x, lp["mlp_norm"], eps)
        mlp = (jax.nn.gelu(h @ lp["w_gate"], approximate=True) * (h @ lp["w_up"])) @ lp["w_down"]
        x = x + _rms_norm_1p(mlp, lp["post_mlp_norm"], eps)
        if fused_prefill:
            # the kernel already committed this layer's K/V to the pool
            if quant:
                return (x, kp_c, vp_c, ksc_c, vsc_c), None
            return (x, kp_c, vp_c), None
        if burst:
            out_kv = (kwin, vwin)
        elif post_write:
            out_kv = (k.astype(pool_dt), v.astype(pool_dt))
        else:
            out_kv = (kp, vp)
        return x, out_kv

    if stream_pools:
        xs = (
            params["layers"],
            jnp.arange(cfg.num_layers, dtype=jnp.int32),
            _layer_windows(cfg),
        )
    elif quant:
        xs = (
            params["layers"], k_pages, v_pages, k_scales, v_scales,
            _layer_windows(cfg),
        )
    else:
        xs = (params["layers"], k_pages, v_pages, _layer_windows(cfg))
    if burst:
        x, (k_acc, v_acc) = lax.scan(layer, x, xs + (k_acc0, v_acc0))
        # no pool write: the caller commits the burst once (deferred mode)
    elif fused_prefill and quant:
        # no post-scan scatter: every layer's kernel wrote its pool + scale
        # slices in place
        (x, k_pages, v_pages, k_scales, v_scales), _ = lax.scan(
            layer, (x, k_pages, v_pages, k_scales, v_scales), xs
        )
    elif fused_prefill:
        # no post-scan scatter: every layer's kernel wrote its pool slice
        (x, k_pages, v_pages), _ = lax.scan(
            layer, (x, k_pages, v_pages), xs
        )
    elif post_write and quant:
        x, (k_new, v_new) = lax.scan(layer, x, xs)
        from production_stack_tpu.ops.quant import (
            write_kv_pages_all_layers_quant,
        )

        k_pages, v_pages, k_scales, v_scales = write_kv_pages_all_layers_quant(
            k_pages, v_pages, k_scales, v_scales, k_new, v_new,
            page_table, positions,
        )
    elif post_write:
        x, (k_new, v_new) = lax.scan(layer, x, xs)
        k_pages, v_pages = write_kv_pages_all_layers(
            k_pages, v_pages, k_new, v_new, page_table, positions
        )
    else:
        x, (k_pages, v_pages) = lax.scan(layer, x, xs)

    x = _rms_norm_1p(x, params["final_norm"], eps)
    if not all_logits:
        # select each sequence's last valid token before the vocab projection
        last_idx = jnp.maximum(jnp.sum(positions >= 0, axis=1) - 1, 0)
        x = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    logits = (x @ params["embed"].T).astype(jnp.float32)
    cap = cfg.final_logit_softcap
    if cap is not None:  # HF checkpoints may null the cap to disable it
        logits = cap * jnp.tanh(logits / cap)
    if burst:
        return logits, k_acc, v_acc
    if quant:
        return logits, k_pages, v_pages, k_scales, v_scales
    return logits, k_pages, v_pages
