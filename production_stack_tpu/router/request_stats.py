"""Per-engine request statistics with sliding windows.

Parity: src/vllm_router/stats/request_stats.py in /root/reference —
RequestStats :34-55, MovingAverageMonitor :58-103, RequestStatsMonitor
lifecycle callbacks :145-236, get_request_stats :238-306.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Optional

from production_stack_tpu.router.utils import SingletonMeta


@dataclasses.dataclass
class RequestStats:
    qps: float = 0.0
    ttft: float = -1.0
    in_prefill_requests: int = 0
    in_decoding_requests: int = 0
    finished_requests: int = 0
    uptime: float = 0.0
    avg_decoding_length: float = -1.0
    avg_latency: float = -1.0
    avg_itl: float = -1.0
    num_swapped_requests: int = 0


class MovingAverageMonitor:
    """Sliding-window average over (timestamp, value) samples."""

    def __init__(self, window: float):
        self.window = window
        self.samples: deque[tuple[float, float]] = deque()

    def update(self, ts: float, value: float) -> None:
        self.samples.append((ts, value))
        self._trim(ts)

    def update_no_value(self, ts: float) -> None:
        self.update(ts, 0.0)

    def _trim(self, now: float) -> None:
        while self.samples and self.samples[0][0] < now - self.window:
            self.samples.popleft()

    def get_average(self) -> float:
        if not self.samples:
            return -1.0
        return sum(v for _, v in self.samples) / len(self.samples)

    def get_sum(self) -> float:
        return sum(v for _, v in self.samples)

    def get_count(self) -> int:
        return len(self.samples)


class RequestStatsMonitor(metaclass=SingletonMeta):
    def __init__(self, sliding_window: float = 60.0):
        self.sliding_window = sliding_window
        self.qps_monitors: dict[str, MovingAverageMonitor] = {}
        self.ttft_monitors: dict[str, MovingAverageMonitor] = {}
        self.latency_monitors: dict[str, MovingAverageMonitor] = {}
        self.decoding_length: dict[str, MovingAverageMonitor] = {}
        self.itl_monitors: dict[str, MovingAverageMonitor] = {}
        # (engine_url, request_id) -> timestamps
        self.request_start: dict[tuple[str, str], float] = {}
        self.first_token: dict[tuple[str, str], float] = {}
        self.last_token: dict[tuple[str, str], float] = {}
        self.tokens_seen: dict[tuple[str, str], int] = {}
        self.in_prefill: dict[str, int] = {}
        self.in_decoding: dict[str, int] = {}
        self.finished: dict[str, int] = {}
        self.swapped: dict[str, int] = {}
        self.first_query: Optional[float] = None

    def _mon(self, d: dict, url: str) -> MovingAverageMonitor:
        if url not in d:
            d[url] = MovingAverageMonitor(self.sliding_window)
        return d[url]

    def on_new_request(self, url: str, request_id: str, ts: Optional[float] = None) -> None:
        ts = ts or time.monotonic()
        if self.first_query is None:
            self.first_query = ts
        self.request_start[(url, request_id)] = ts
        self.in_prefill[url] = self.in_prefill.get(url, 0) + 1
        self._mon(self.qps_monitors, url).update_no_value(ts)

    def on_request_response(self, url: str, request_id: str, ts: Optional[float] = None) -> None:
        """First token received: prefill -> decode."""
        key = (url, request_id)
        if key not in self.request_start or key in self.first_token:
            return
        ts = ts or time.monotonic()
        self.first_token[key] = ts
        self.last_token[key] = ts
        self.tokens_seen[key] = 1
        self.in_prefill[url] = max(0, self.in_prefill.get(url, 0) - 1)
        self.in_decoding[url] = self.in_decoding.get(url, 0) + 1
        self._mon(self.ttft_monitors, url).update(ts, ts - self.request_start[key])

    def on_token(self, url: str, request_id: str, ts: Optional[float] = None) -> None:
        key = (url, request_id)
        if key not in self.first_token:
            return
        ts = ts or time.monotonic()
        prev = self.last_token.get(key, ts)
        self._mon(self.itl_monitors, url).update(ts, ts - prev)
        self.last_token[key] = ts
        self.tokens_seen[key] = self.tokens_seen.get(key, 0) + 1

    def on_request_complete(self, url: str, request_id: str, ts: Optional[float] = None) -> None:
        key = (url, request_id)
        start = self.request_start.pop(key, None)
        ts = ts or time.monotonic()
        if key in self.first_token:
            self.in_decoding[url] = max(0, self.in_decoding.get(url, 0) - 1)
            self._mon(self.decoding_length, url).update(ts, self.tokens_seen.get(key, 0))
        else:
            self.in_prefill[url] = max(0, self.in_prefill.get(url, 0) - 1)
        self.finished[url] = self.finished.get(url, 0) + 1
        if start is not None:
            self._mon(self.latency_monitors, url).update(ts, ts - start)
        self.first_token.pop(key, None)
        self.last_token.pop(key, None)
        self.tokens_seen.pop(key, None)

    def on_request_swapped(self, url: str, request_id: str) -> None:
        self.swapped[url] = self.swapped.get(url, 0) + 1

    def get_request_stats(self, now: Optional[float] = None) -> dict[str, RequestStats]:
        now = now or time.monotonic()
        out: dict[str, RequestStats] = {}
        urls = (
            set(self.qps_monitors) | set(self.in_prefill) | set(self.in_decoding)
            | set(self.finished)
        )
        for url in urls:
            qps_mon = self.qps_monitors.get(url)
            if qps_mon is not None:
                qps_mon._trim(now)
                qps = qps_mon.get_count() / self.sliding_window
            else:
                qps = 0.0
            ttft_mon = self.ttft_monitors.get(url)
            lat_mon = self.latency_monitors.get(url)
            itl_mon = self.itl_monitors.get(url)
            dec_mon = self.decoding_length.get(url)
            out[url] = RequestStats(
                qps=qps,
                ttft=ttft_mon.get_average() if ttft_mon else -1.0,
                in_prefill_requests=self.in_prefill.get(url, 0),
                in_decoding_requests=self.in_decoding.get(url, 0),
                finished_requests=self.finished.get(url, 0),
                uptime=(now - self.first_query) if self.first_query else 0.0,
                avg_decoding_length=dec_mon.get_average() if dec_mon else -1.0,
                avg_latency=lat_mon.get_average() if lat_mon else -1.0,
                avg_itl=itl_mon.get_average() if itl_mon else -1.0,
                num_swapped_requests=self.swapped.get(url, 0),
            )
        return out


def initialize_request_stats_monitor(sliding_window: float = 60.0) -> RequestStatsMonitor:
    return RequestStatsMonitor(sliding_window)


def get_request_stats_monitor() -> RequestStatsMonitor:
    return RequestStatsMonitor()
