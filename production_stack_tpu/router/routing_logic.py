"""Routing algorithms.

Parity: src/vllm_router/routers/routing_logic.py in /root/reference —
roundrobin :126-157, session (consistent hash ring) :160-209, kvaware (global
KV-index lookup) :212-329, prefixaware (HashTrie) :332-408,
disaggregated_prefill :411-451, QPS fallback _qps_routing :59-81,
initialize/reconfigure/get :455-511.

The KV-aware router queries this stack's own KV-index controller
(kvoffload/controller.py) — the TPU-native replacement for the LMCache
controller ZMQ protocol the reference router speaks.
"""

from __future__ import annotations

import abc
import asyncio
import hashlib
import time
from typing import Any, Optional

from production_stack_tpu.router.hashtrie import HashTrie
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.router.utils import SingletonMeta
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class RoutingInterface(metaclass=SingletonMeta):
    @abc.abstractmethod
    async def route_request(
        self,
        endpoints: list[EndpointInfo],
        engine_stats: dict[str, Any],
        request_stats: dict[str, Any],
        request: Any,
        request_json: Optional[dict] = None,
    ) -> str: ...

    @staticmethod
    def breaker_filtered(endpoints: list[EndpointInfo]) -> list[EndpointInfo]:
        """Passive-circuit-breaker consultation: drop endpoints whose breaker
        is open (fail-static — an all-open set passes through unchanged).
        Idempotent, so request_service pre-filtering composes with routing
        implementations that call this themselves."""
        from production_stack_tpu.router.resilience import get_breaker_registry

        return get_breaker_registry().filter_endpoints(endpoints)

    @staticmethod
    def saturation_filtered(
        endpoints: list[EndpointInfo], engine_stats: Optional[dict] = None
    ) -> list[EndpointInfo]:
        """Deprioritize saturated backends: drop endpoints currently inside
        a shed window (a recent 429 + Retry-After) or whose scraped stats
        report ``vllm:engine_saturated`` — they have no capacity for new
        non-sticky traffic. Fail-static: when EVERY candidate is saturated
        the original set passes through unchanged, so the requests reach an
        engine whose own 429 (with Retry-After) is the correct client
        answer — never a synthesized router error."""
        from production_stack_tpu.router.resilience import get_saturation_registry

        reg = get_saturation_registry()
        out = []
        for ep in endpoints:
            if reg.is_saturated(ep.url):
                continue
            es = (engine_stats or {}).get(ep.url)
            if es is not None and getattr(es, "engine_saturated", 0):
                continue
            out.append(ep)
        return out if out else list(endpoints)

    @staticmethod
    def class_filtered(
        endpoints: list[EndpointInfo],
        priority: str,
        min_attainment: float = 0.9,
    ) -> list[EndpointInfo]:
        """Class-aware placement (docs/failure-handling.md priority classes):
        batch traffic avoids backends whose *interactive* TTFT SLO attainment
        has degraded below ``min_attainment``, keeping bulk work off engines
        that are already failing their latency-sensitive tenants. Interactive
        traffic is never filtered here — it sees every candidate. Fail-static
        like the saturation filter: if every backend is degraded (or none has
        attainment data yet) the original set passes through unchanged, so
        batch requests still land somewhere and the engine-side admission
        control (which sheds batch first) gives the honest 429."""
        if priority != "batch" or min_attainment <= 0.0:
            return list(endpoints)
        from production_stack_tpu.router.slo import get_slo_monitor

        mon = get_slo_monitor()
        out = []
        for ep in endpoints:
            att = mon.interactive_attainment(ep.url, "ttft")
            if att is not None and att < min_attainment:
                continue
            out.append(ep)
        return out if out else list(endpoints)


def _qps_routing(endpoints: list[EndpointInfo], request_stats: dict[str, Any]) -> str:
    """Lowest-QPS endpoint (parity :59-81)."""
    best, best_qps = None, float("inf")
    for ep in endpoints:
        rs = request_stats.get(ep.url)
        qps = rs.qps if rs is not None else -1
        if qps < best_qps:
            best, best_qps = ep.url, qps
    if best is None:
        raise ValueError("no endpoints to route to")
    return best


class RoundRobinRouter(RoutingInterface):
    def __init__(self):
        self.idx = 0

    async def route_request(self, endpoints, engine_stats, request_stats, request,
                            request_json=None) -> str:
        urls = sorted(ep.url for ep in endpoints)
        url = urls[self.idx % len(urls)]
        self.idx += 1
        return url


class HashRing:
    """Consistent-hash ring with virtual nodes (uhashring replacement)."""

    VNODES = 100

    def __init__(self, nodes: Optional[list[str]] = None):
        self._ring: list[tuple[int, str]] = []
        self._nodes: set[str] = set()
        for n in nodes or []:
            self.add_node(n)

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "little")

    def add_node(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for v in range(self.VNODES):
            self._ring.append((self._hash(f"{node}#{v}"), node))
        self._ring.sort()

    def remove_node(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._ring = [(h, n) for h, n in self._ring if n != node]

    def get_nodes(self) -> set[str]:
        return set(self._nodes)

    def get_node(self, key: str) -> str:
        if not self._ring:
            raise ValueError("hash ring is empty")
        h = self._hash(key)
        import bisect

        i = bisect.bisect_right(self._ring, (h, chr(0x10FFFF)))
        if i == len(self._ring):
            i = 0
        return self._ring[i][1]


class SessionRouter(RoutingInterface):
    """Sticky sessions via consistent hashing on a header/param key
    (parity :160-209)."""

    def __init__(self, session_key: Optional[str] = None):
        if not session_key:
            raise ValueError("session routing requires --session-key")
        self.session_key = session_key
        self.ring = HashRing()

    def _sync_ring(self, endpoints: list[EndpointInfo]) -> None:
        urls = {ep.url for ep in endpoints}
        for gone in self.ring.get_nodes() - urls:
            self.ring.remove_node(gone)
        for new in urls - self.ring.get_nodes():
            self.ring.add_node(new)

    async def route_request(self, endpoints, engine_stats, request_stats, request,
                            request_json=None) -> str:
        session_id = None
        headers = getattr(request, "headers", None)
        if headers is not None:
            session_id = headers.get(self.session_key)
        if session_id is None and request_json:
            session_id = request_json.get(self.session_key)
        self._sync_ring(endpoints)
        if not session_id:
            return _qps_routing(endpoints, request_stats)
        # migration re-pin (docs/migration.md): a session whose stream was
        # live-migrated is pinned to its new backend — the hash ring is
        # deterministic and would bounce it straight back, undoing the
        # controller's rebalance on the very next request
        from production_stack_tpu.router.resilience import get_session_pins

        pinned = get_session_pins().lookup(str(session_id))
        if pinned is not None and any(ep.url == pinned for ep in endpoints):
            return pinned
        return self.ring.get_node(str(session_id))


class PrefixAwareRouter(RoutingInterface):
    """Route to the endpoint that has seen the longest prefix of this prompt
    (parity :332-408); falls back to lowest-QPS among tied candidates."""

    def __init__(self):
        self.trie = HashTrie()
        # endpoints ever inserted into the trie, for the discovery-dropout
        # sweep (ISSUE 9 bugfix): the trie retained entries for backends
        # removed from service discovery, so a departed backend kept winning
        # locality scores forever — mirrors engine_stats' _dropped_stale
        # bookkeeping for config-removed urls
        self._trie_urls: set[str] = set()

    @classmethod
    def make_fallback(cls) -> "PrefixAwareRouter":
        """A NON-singleton instance for use as another router's fallback:
        ``cls()`` goes through SingletonMeta and would hand back (and
        register) THE shared prefixaware router — the fallback must be
        private state. Keep this the single place the fields are initialized
        so the __new__ bypass cannot drift from __init__."""
        r = cls.__new__(cls)
        r.trie = HashTrie()
        r._trie_urls = set()
        return r

    async def sweep_departed(self, current_urls: set) -> None:
        """Drop trie claims of endpoints no longer in service discovery. A
        swept backend that returns re-learns its locality from scratch —
        correct for both a config removal and a restart (its cache is cold
        either way)."""
        gone = self._trie_urls - current_urls
        for url in gone:
            await self.trie.remove_endpoint(url)
            logger.info(
                "prefix trie: swept departed backend %s (%d still tracked)",
                url, len(self._trie_urls) - 1,
            )
        self._trie_urls -= gone

    async def _sweep_with_discovery(self) -> None:
        from production_stack_tpu.router.service_discovery import (
            get_service_discovery,
        )

        try:
            sd = get_service_discovery()
        except Exception:  # noqa: BLE001 - unit tests route without discovery
            return
        await self.sweep_departed({ep.url for ep in sd.get_endpoint_info()})

    @staticmethod
    def _prompt_of(request_json: Optional[dict]) -> Optional[str]:
        if not request_json:
            return None
        if "prompt" in request_json:
            p = request_json["prompt"]
            return p if isinstance(p, str) else (p[0] if p else None)
        if "messages" in request_json:
            return "".join(
                str(m.get("content", "")) for m in request_json["messages"]
            )
        return None

    async def route_request(self, endpoints, engine_stats, request_stats, request,
                            request_json=None) -> str:
        await self._sweep_with_discovery()
        available = {ep.url for ep in endpoints}
        prompt = self._prompt_of(request_json)
        if prompt is None:
            return _qps_routing(endpoints, request_stats)
        matched, candidates = await self.trie.longest_prefix_match(prompt, available)
        candidate_eps = [ep for ep in endpoints if ep.url in candidates]
        url = _qps_routing(candidate_eps or endpoints, request_stats)
        await self.trie.insert(prompt, url)
        self._trie_urls.add(url)
        return url


class KvawareRouter(RoutingInterface):
    """KV-aware routing.

    v1 (parity :212-329): query the KV-index controller for the instance
    holding the longest cached token prefix (LMCache controller protocol
    replaced by kvoffload/controller.py).

    v2 (ISSUE 9, docs/kv-directory.md): consult the fleet-wide KV directory
    hosted by the cache server and rank backends
    **resident > restorable > cold** —

    - *resident*: a backend already holds the longest prefix in its HBM
      prefix cache (the directory's generation-fenced resident claims);
    - *restorable*: the prefix's blobs sit in the shared cache-server tier,
      so ANY backend can pull them before prefill. Weighted by what the
      target would actually restore: each engine exports its
      linkprobe-derived per-operation restore cap
      (vllm:kv_offload_max_io_pages — the engine-measured
      restore-vs-recompute crossover, engine/linkprobe.py), scraped into
      EngineStats; restorable tokens beyond cap x page_size would recompute
      anyway and score zero. Ties break to the lowest-QPS backend;
    - *cold*: nothing known — fall through to the prefix-trie fallback.

    Both modes learn the outcome into the fallback trie, so a directory or
    controller outage degrades to prefixaware, not roundrobin."""

    def __init__(
        self,
        controller_url: Optional[str] = None,
        tokenizer_path: Optional[str] = None,
        directory_url: Optional[str] = None,
    ):
        if not controller_url and not directory_url:
            raise ValueError(
                "kvaware routing requires --kv-controller-url or "
                "--kv-directory-url"
            )
        self.controller_url = controller_url
        self.directory_url = directory_url
        from production_stack_tpu.engine.tokenizer import load_tokenizer

        self.tokenizer = load_tokenizer(tokenizer_path)
        self._client = None
        self._dir_client = None
        # vllm_router:kvaware_v2_{resident,restorable,cold}_routes_total
        self.route_class_counts = {"resident": 0, "restorable": 0, "cold": 0}
        self.fallback = PrefixAwareRouter.make_fallback()

    async def _lookup(self, tokens: list[int]) -> Optional[str]:
        from production_stack_tpu.kvoffload.controller import ControllerClient

        try:
            if self._client is None:
                self._client = ControllerClient(self.controller_url)
            return await self._client.lookup_url(tokens)
        except Exception as e:
            logger.warning("kv controller lookup failed: %s", e)
            self._client = None
            return None

    async def _dir_lookup(self, tokens: list[int]) -> Optional[dict]:
        from production_stack_tpu.kvdirectory import DirectoryClient

        try:
            if self._dir_client is None:
                self._dir_client = DirectoryClient(self.directory_url)
            return await self._dir_client.lookup(tokens)
        except Exception as e:
            logger.warning("kv directory lookup failed: %s", e)
            self._dir_client = None
            return None

    @staticmethod
    def _restorable_tokens(restorable: dict, es, page_size: Optional[int]) -> int:
        """Tokens a backend would actually restore from the shared tier: the
        per-page-size restorable depth, clamped by the backend's exported
        restore cap. ``page_size`` is the backend's registered page size
        from the directory — chunk identity is page-size-dependent, so a
        backend is only credited the chain hashed at ITS page size (unknown
        backends fall back to the best chain, optimistically). Cap semantics
        follow the engine's export (engine/linkprobe.py): 0 = fast link,
        restore unbounded; N > 0 = slow link, N pages is the
        restore-vs-recompute crossover; the metric ABSENT from a scraped
        backend (-1 here) means it has NO offload tiers at all — it cannot
        pull anything, score 0. A backend with no stats yet (never scraped)
        is scored optimistically unbounded: the directory is a hint and a
        wrong pick only costs a recompute."""
        if es is None:
            cap = 0.0  # unscraped: optimistic
        else:
            cap = getattr(es, "kv_offload_max_io_pages", 0.0)
            if cap is None or cap < 0:
                return 0  # scraped, metric absent: no offload tiers
        if page_size is not None:
            restorable = {
                k: v for k, v in restorable.items() if int(k) == page_size
            }
        best = 0
        for ps_str, toks in restorable.items():
            ps = int(ps_str)
            eff = int(toks) if cap <= 0 else min(int(toks), int(cap) * ps)
            best = max(best, eff)
        return best

    def _rank_v2(self, res: dict, endpoints, engine_stats, request_stats):
        """resident > restorable > cold; returns (class, url|None)."""
        urls = {ep.url for ep in endpoints}
        best_url, best_tokens = None, 0
        for url, info in (res.get("engines") or {}).items():
            if url in urls and int(info.get("resident_tokens", 0)) > best_tokens:
                best_url, best_tokens = url, int(info["resident_tokens"])
        if best_url is not None:
            return "resident", best_url
        restorable = res.get("restorable") or {}
        if restorable:
            page_sizes = res.get("page_sizes") or {}
            scored = [
                (ep, self._restorable_tokens(
                    restorable, (engine_stats or {}).get(ep.url),
                    page_sizes.get(ep.url),
                ))
                for ep in endpoints
            ]
            top = max((s for _, s in scored), default=0)
            if top > 0:
                tied = [ep for ep, s in scored if s == top]
                return "restorable", _qps_routing(tied, request_stats)
        return "cold", None

    async def route_request(self, endpoints, engine_stats, request_stats, request,
                            request_json=None) -> str:
        prompt = PrefixAwareRouter._prompt_of(request_json)
        if prompt is not None:
            tokens = self.tokenizer.encode(prompt)
            if self.directory_url:
                res = await self._dir_lookup(tokens)
                if res is not None:
                    cls, url = self._rank_v2(
                        res, endpoints, engine_stats, request_stats
                    )
                    self.route_class_counts[cls] += 1
                    if url is not None:
                        # teach the fallback trie the outcome so a later
                        # directory outage keeps this locality
                        await self.fallback.trie.insert(prompt, url)
                        self.fallback._trie_urls.add(url)
                        return url
            if self.controller_url:
                url = await self._lookup(tokens)
                if url and any(ep.url == url for ep in endpoints):
                    return url
        return await self.fallback.route_request(
            endpoints, engine_stats, request_stats, request, request_json
        )


class DisaggregatedPrefillRouter(RoutingInterface):
    """Pick a (prefill, decode) endpoint pair by model labels
    (parity :411-451; the two-phase HTTP flow lives in request_service)."""

    def __init__(self, prefill_labels: list[str], decode_labels: list[str]):
        self.prefill_labels = prefill_labels
        self.decode_labels = decode_labels
        self._rr = {"prefill": 0, "decode": 0}
        # decode picks that were transfer-cost-aware (fabric bandwidth known
        # for at least one candidate) — vllm_router:disagg_fabric_routes_total
        self.fabric_routes = 0

    def _pick(self, endpoints: list[EndpointInfo], labels: list[str], kind: str) -> str:
        # breaker-aware even for direct route_prefill/route_decode callers —
        # but the breaker filter runs AFTER label selection so fail-static is
        # per ROLE: when every prefill-labeled pod is tripped, keep trying
        # the tripped prefillers rather than silently re-homing prefill
        # traffic onto decode-labeled pods
        role = [ep for ep in endpoints if ep.model_label in labels] or list(endpoints)
        role = self.breaker_filtered(role)
        if kind == "decode" and len(role) > 1:
            # transfer-cost-aware decode placement (docs/kv-fabric.md, NetKV):
            # the prefiller streams the prompt's KV to whichever decoder we
            # pick, so prefer the one with the best probed fabric bandwidth
            # per unit of fabric queue depth — scraped off each engine's
            # /metrics by the stats scraper. Engines without fabric (bw==0)
            # yield no score and the pool stays round-robin.
            url = self._fabric_pick(role)
            if url is not None:
                self.fabric_routes += 1
                return url
        pool = sorted(ep.url for ep in role)
        url = pool[self._rr[kind] % len(pool)]
        self._rr[kind] += 1
        return url

    @staticmethod
    def _fabric_pick(role: list[EndpointInfo]) -> Optional[str]:
        from production_stack_tpu.kvfabric.peers import pick_best_peer
        from production_stack_tpu.router.engine_stats import (
            get_engine_stats_scraper,
        )

        try:
            stats = get_engine_stats_scraper().get_engine_stats()
        except Exception:  # noqa: BLE001 - scraper not running: RR fallback
            return None
        candidates = []
        for ep in role:
            st = stats.get(ep.url)
            if st is None:
                continue
            candidates.append((
                ep.url,
                st.kv_fabric_peer_bandwidth_bytes_per_sec,
                st.kv_fabric_queue_depth,
            ))
        return pick_best_peer(candidates)

    async def route_request(self, endpoints, engine_stats, request_stats, request,
                            request_json=None) -> str:
        # plain route_request returns the decode endpoint; request_service
        # calls route_prefill/route_decode explicitly for the 2-phase flow
        return self._pick(endpoints, self.decode_labels, "decode")

    def route_prefill(self, endpoints: list[EndpointInfo]) -> str:
        return self._pick(endpoints, self.prefill_labels, "prefill")

    def route_decode(self, endpoints: list[EndpointInfo]) -> str:
        return self._pick(endpoints, self.decode_labels, "decode")


_router: Optional[RoutingInterface] = None


def render_kvaware_metrics() -> list[str]:
    """Prometheus lines for the KV-aware-v2 route-class counters (rendered
    by router/app.py /metrics; zero-valued when kvaware v2 is not active so
    dashboard queries always resolve)."""
    counts = (
        _router.route_class_counts
        if isinstance(_router, KvawareRouter)
        else {}
    )
    lines = []
    for name, key in (
        ("vllm_router:kvaware_v2_resident_routes_total", "resident"),
        ("vllm_router:kvaware_v2_restorable_routes_total", "restorable"),
        ("vllm_router:kvaware_v2_cold_routes_total", "cold"),
    ):
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {counts.get(key, 0)}")
    # disagg decode picks that used fabric transfer-cost scoring instead of
    # round-robin (docs/kv-fabric.md; zero-valued outside disagg mode)
    fabric_routes = (
        _router.fabric_routes
        if isinstance(_router, DisaggregatedPrefillRouter)
        else 0
    )
    lines.append("# TYPE vllm_router:disagg_fabric_routes_total counter")
    lines.append(f"vllm_router:disagg_fabric_routes_total {fabric_routes}")
    return lines


def initialize_routing_logic(
    routing_logic: str,
    *,
    session_key: Optional[str] = None,
    kv_controller_url: Optional[str] = None,
    kv_directory_url: Optional[str] = None,
    tokenizer_path: Optional[str] = None,
    prefill_model_labels: Optional[list[str]] = None,
    decode_model_labels: Optional[list[str]] = None,
) -> RoutingInterface:
    global _router
    # reset only routing singletons (reconfigure support) — other singletons
    # (stats scraper, request monitor) must survive a routing swap
    for cls in list(SingletonMeta._instances):
        if issubclass(cls, RoutingInterface):
            SingletonMeta._instances.pop(cls)
    if routing_logic == "roundrobin":
        _router = RoundRobinRouter()
    elif routing_logic == "session":
        _router = SessionRouter(session_key)
    elif routing_logic == "prefixaware":
        _router = PrefixAwareRouter()
    elif routing_logic == "kvaware":
        _router = KvawareRouter(
            kv_controller_url, tokenizer_path, directory_url=kv_directory_url
        )
    elif routing_logic == "disaggregated_prefill":
        _router = DisaggregatedPrefillRouter(
            prefill_model_labels or [], decode_model_labels or []
        )
    else:
        raise ValueError(f"unknown routing logic: {routing_logic}")
    logger.info("initialized routing logic: %s", routing_logic)
    return _router


def reconfigure_routing_logic(routing_logic: str, **kwargs) -> RoutingInterface:
    return initialize_routing_logic(routing_logic, **kwargs)


def get_routing_logic() -> RoutingInterface:
    assert _router is not None, "routing logic not initialized"
    return _router
