"""Router-side SLO accounting: per-model/per-backend attainment counters and
the fleet saturation gauge (ISSUE 7 tentpole b; docs/observability.md).

The engine attributes every finished request a terminal record (queue wait,
TTFT, inter-token p99, token counts, KV pages peak, outcome) in a bounded log
served by ``GET /slo_records?since=<cursor>``; the stats scraper
(engine_stats.py) polls it per backend each scrape interval and feeds the
records here. This module applies the router's configured objectives and
exports, on the router's ``/metrics``:

- ``vllm_router:slo_attained_total{objective,model,priority,server}`` /
  ``vllm_router:slo_violated_total{...}`` — per-objective counters, split
  by the request's SLO class (``priority="interactive"|"batch"``):
  * ``objective="ttft"``        — TTFT <= --slo-ttft-ms (ok requests only)
  * ``objective="itl"``         — inter-token p99 <= --slo-itl-ms
  * ``objective="availability"``— the request finished ok at all (sheds,
    aborts, and errors violate; they have no honest latency to judge)
- ``vllm_router:slo_request_outcomes_total{outcome,server}`` — terminal
  outcome counts (ok / shed / abort / error / migrated; a "migrated" record
  is the SOURCE side of a live migration and abstains from every latency
  and availability objective — the target attributes the real terminal).
- ``vllm_router:slo_records_total{server}`` — records ingested (a flat line
  while traffic flows means the backend's /slo_records scrape is broken).
- ``vllm_router:fleet_saturation`` — a single [0, 1] gauge: the mean
  per-backend saturation score, where a backend inside a shed Retry-After
  window or reporting ``vllm:engine_saturated`` scores 1.0 and otherwise
  its waiting-queue depth scores ``min(1, waiting / --saturation-queue-ref)``.
  This is the prometheus-adapter autoscaling signal
  (observability/prom-adapter.yaml exports it as ``tpu_fleet_saturation``):
  unlike raw QPS it rises with *pressure* (queue growth, sheds) rather than
  with traffic the fleet is absorbing fine, and unlike
  ``num_requests_waiting`` alone it is normalized to fleet size so the HPA
  target is a stable fraction.

All counters are label-bounded: objective/outcome are closed enums, model
and server come from service discovery (no per-request labels — the
cardinality test in tests/test_tracing.py enforces this stack-wide).
"""

from __future__ import annotations

from typing import Iterable, Optional

from production_stack_tpu.router.utils import SingletonMeta
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

OBJECTIVES = ("ttft", "itl", "availability")
OUTCOMES = ("ok", "shed", "abort", "error", "migrated")
# per-request SLO classes (docs/failure-handling.md priority classes): a
# closed label set — records carrying anything else degrade to interactive
PRIORITIES = ("interactive", "batch")


class SLOMonitor(metaclass=SingletonMeta):
    def __init__(
        self,
        ttft_ms: float = 2000.0,
        itl_ms: float = 200.0,
        saturation_queue_ref: int = 8,
    ):
        self.ttft_ms = float(ttft_ms)
        self.itl_ms = float(itl_ms)
        self.saturation_queue_ref = max(1, int(saturation_queue_ref))
        # per-backend /slo_records cursor (the scraper reads + advances it)
        self._cursors: dict[str, int] = {}
        # (server, model, objective, priority) -> [attained, violated] —
        # same two families, one extra closed-set label, so per-class
        # attainment is scrapeable without new metric names
        self._counters: dict[tuple, list] = {}
        # (server, outcome) -> count
        self._outcomes: dict[tuple, int] = {}
        self._records_total: dict[str, int] = {}

    # -- scrape protocol -----------------------------------------------------

    def cursor(self, url: str) -> int:
        return self._cursors.get(url, 0)

    def ingest(self, url: str, payload: dict) -> int:
        """Apply one /slo_records response; returns records consumed. A
        ``head`` below our cursor means the engine restarted (fresh record
        counter) — reset so the next scrape picks the new incarnation's
        records up from zero instead of waiting out the old watermark."""
        try:
            head = int(payload.get("head", 0))
            records = payload.get("records") or []
        except AttributeError:
            return 0
        since = self._cursors.get(url, 0)
        if head < since:
            self._cursors[url] = 0
            return 0
        n = 0
        for rec in records:
            try:
                self._apply(url, rec)
                n += 1
            except (AttributeError, TypeError, KeyError, ValueError):
                continue  # malformed record must not poison the batch
        self._cursors[url] = max(since, int(payload.get("next", since)))
        return n

    def _bump(
        self,
        server: str,
        model: str,
        objective: str,
        attained: bool,
        priority: str = "interactive",
    ):
        key = (server, model, objective, priority)
        cell = self._counters.get(key)
        if cell is None:
            cell = self._counters[key] = [0, 0]
        cell[0 if attained else 1] += 1

    def _apply(self, url: str, rec: dict) -> None:
        model = str(rec.get("model") or "unknown")
        outcome = str(rec.get("outcome") or "error")
        if outcome not in OUTCOMES:
            outcome = "error"
        priority = str(rec.get("priority") or "interactive")
        if priority not in PRIORITIES:
            priority = "interactive"
        self._records_total[url] = self._records_total.get(url, 0) + 1
        self._outcomes[(url, outcome)] = self._outcomes.get((url, outcome), 0) + 1
        if outcome == "migrated":
            # the stream continues on another engine, which attributes the
            # REAL terminal record when it finishes — the source's handoff
            # record is diagnostic only. Counting it as an availability
            # violation would charge every rebalance as an outage; counting
            # it attained would double-count the request.
            return
        self._bump(url, model, "availability", outcome == "ok", priority)
        if outcome != "ok":
            # a shed/abort/error has no honest latency to judge: it violates
            # availability, and the latency objectives abstain (counting it
            # as a TTFT violation too would double-charge one failure)
            return
        ttft = rec.get("ttft_ms")
        if ttft is not None:
            self._bump(url, model, "ttft", float(ttft) <= self.ttft_ms,
                       priority)
        itl = rec.get("itl_p99_ms")
        if itl is not None:
            self._bump(url, model, "itl", float(itl) <= self.itl_ms,
                       priority)

    def interactive_attainment(
        self, server: str, objective: str = "ttft"
    ) -> Optional[float]:
        """Interactive-class attainment ratio for one backend and objective
        (all models summed), or None before any interactive record landed.
        The router's class-aware placement reads this: batch traffic avoids
        backends whose interactive attainment is degraded, and the fleet
        controller corroborates its engine-side latency watermark with it."""
        att = vio = 0
        for (srv, _model, obj, pri), cell in self._counters.items():
            if srv == server and obj == objective and pri == "interactive":
                att += cell[0]
                vio += cell[1]
        total = att + vio
        return (att / total) if total else None

    def forget(self, url: str) -> None:
        """Drop a backend's cursor. NOT called on discovery dropout — a
        flapping (but not restarted) backend rejoining would re-serve its
        retained records from seq 0 and double-count; ``ingest``'s
        head-below-cursor check already handles real restarts. Kept for
        tests and manual resets (counters persist either way — Prometheus
        counters must not vanish mid-series)."""
        self._cursors.pop(url, None)

    # -- fleet saturation ----------------------------------------------------

    def fleet_saturation(
        self,
        engine_stats: dict,
        shedding_urls: Optional[Iterable[str]] = None,
    ) -> float:
        """Mean per-backend saturation score in [0, 1] (see module doc)."""
        shedding = set(shedding_urls or ())
        urls = set(engine_stats) | shedding
        if not urls:
            return 0.0
        total = 0.0
        for url in urls:
            es = engine_stats.get(url)
            if url in shedding or (
                es is not None and getattr(es, "engine_saturated", 0)
            ):
                total += 1.0
            elif es is not None:
                waiting = float(getattr(es, "num_queuing_requests", 0) or 0)
                total += min(1.0, waiting / self.saturation_queue_ref)
        return total / len(urls)

    # -- exposition ----------------------------------------------------------

    def render(self, fleet_saturation: Optional[float] = None) -> list[str]:
        lines = [
            "# TYPE vllm_router:slo_attained_total counter",
            "# TYPE vllm_router:slo_violated_total counter",
        ]
        for (server, model, objective, priority), (att, vio) in sorted(
            self._counters.items()
        ):
            lab = (
                f'objective="{objective}",model="{model}"'
                f',priority="{priority}",server="{server}"'
            )
            lines.append(f"vllm_router:slo_attained_total{{{lab}}} {att}")
            lines.append(f"vllm_router:slo_violated_total{{{lab}}} {vio}")
        lines.append("# TYPE vllm_router:slo_request_outcomes_total counter")
        for (server, outcome), n in sorted(self._outcomes.items()):
            lines.append(
                f"vllm_router:slo_request_outcomes_total"
                f'{{outcome="{outcome}",server="{server}"}} {n}'
            )
        lines.append("# TYPE vllm_router:slo_records_total counter")
        for server, n in sorted(self._records_total.items()):
            lines.append(
                f'vllm_router:slo_records_total{{server="{server}"}} {n}'
            )
        if fleet_saturation is not None:
            lines += [
                "# TYPE vllm_router:fleet_saturation gauge",
                f"vllm_router:fleet_saturation {round(fleet_saturation, 4)}",
            ]
        return lines


def initialize_slo_monitor(
    ttft_ms: float = 2000.0,
    itl_ms: float = 200.0,
    saturation_queue_ref: int = 8,
) -> SLOMonitor:
    return SLOMonitor(ttft_ms, itl_ms, saturation_queue_ref)


def get_slo_monitor() -> SLOMonitor:
    return SLOMonitor()
