"""Request proxying: the router's data plane.

Parity: src/vllm_router/services/request_service/request.py in /root/reference —
process_request (streaming proxy + stats hooks) :54-138, route_general_request
(discovery, alias/sleep filtering, routing, response headers) :141-304,
disaggregated prefill two-phase flow :307-439, sleep/wake proxying :442-514.

The "Routing request <id> ... to <url> at <t>" log line format is load-bearing:
the reference's e2e tests assert on it (tests/e2e/test-routing.py) and ours do
too (SURVEY.md §4.3).
"""

from __future__ import annotations

import collections
import json
import time
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.router.routing_logic import (
    DisaggregatedPrefillRouter,
    get_routing_logic,
)
from production_stack_tpu.router.engine_stats import get_engine_stats_scraper
from production_stack_tpu.router.request_stats import get_request_stats_monitor
from production_stack_tpu.router.service_discovery import EndpointInfo, get_service_discovery
from production_stack_tpu.tracing import TRACEPARENT_HEADER, get_collector
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

_client_session: Optional[aiohttp.ClientSession] = None

# Per-request TTFT hop samples, (recv->route, route->backend-headers,
# backend-headers->first-chunk) in ms. /metrics exposes p50/p99 per hop so
# tail latency is attributable to a stage, not just "the stack".
_hop_samples: collections.deque = collections.deque(maxlen=2048)

# Router-observed TTFT / e2e latency distributions (reference dashboard's
# heatmap panels; vLLM-compatible names + buckets — utils/metrics.py)
from production_stack_tpu.utils.metrics import (  # noqa: E402
    LATENCY_BUCKETS,
    TTFT_BUCKETS,
    Histogram,
)

ttft_hist = Histogram(
    # vllm_router: namespace, NOT vllm: — a Prometheus scraping both router
    # and engine would otherwise double-count every request in the
    # dashboard's distribution heatmaps (each request is observed once by
    # each server under the same series name)
    "vllm_router:time_to_first_token_seconds", TTFT_BUCKETS,
    "Time to first token distribution (router-observed)",
)
latency_hist = Histogram(
    "vllm_router:e2e_request_latency_seconds", LATENCY_BUCKETS,
    "End-to-end request latency distribution (router-observed)",
)


def record_hop_sample(recv_to_route: float, route_to_connect: float,
                      connect_to_first: float) -> None:
    _hop_samples.append((recv_to_route, route_to_connect, connect_to_first))
    ttft_hist.observe((recv_to_route + route_to_connect + connect_to_first) / 1000)


def reset_hop_samples() -> None:
    """Clear the hop sample window (POST /metrics/reset): a benchmark phase
    scrapes then resets, so each phase's quantiles describe THAT phase's
    requests instead of pooling across differently-loaded phases."""
    _hop_samples.clear()
    ttft_hist.reset()
    latency_hist.reset()


def get_hop_quantiles() -> dict:
    """{hop: {p50, p99}} in ms over the sample window."""
    if not _hop_samples:
        return {}
    cols = list(zip(*_hop_samples))
    names = ("recv_to_route", "route_to_connect", "connect_to_first_chunk")
    out = {}
    for name, vals in zip(names, cols):
        s = sorted(vals)
        out[name] = {
            "p50": s[len(s) // 2],
            "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
        }
    return out


async def get_client_session() -> aiohttp.ClientSession:
    """Shared connection-pooled client (parity: httpx_client.py)."""
    global _client_session
    if _client_session is None or _client_session.closed:
        _client_session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10),
            connector=aiohttp.TCPConnector(limit=0),
        )
    return _client_session


async def close_client_session() -> None:
    global _client_session
    if _client_session and not _client_session.closed:
        await _client_session.close()
    _client_session = None


def _filter_headers(headers) -> dict:
    hop = {"host", "content-length", "transfer-encoding", "connection"}
    return {k: v for k, v in headers.items() if k.lower() not in hop}


async def process_request(
    request: web.Request,
    body: bytes,
    backend_url: str,
    endpoint: str,
    request_id: str,
    *,
    is_streaming: bool,
    capture_body: Optional[object] = None,
    ts_recv: Optional[float] = None,
    trace_ctx=None,
) -> web.StreamResponse:
    """Proxy `body` to backend and stream the response back, firing request
    stats callbacks (parity request.py:54-138).

    `capture_body(status, bytes)` — optional async callback fired with the full
    response once the proxy completes (semantic-cache store, post_request
    callbacks). ``ts_recv`` is the perf_counter when the router first saw the
    request, for the per-hop TTFT breakdown. ``trace_ctx`` is the router's
    request-level span context; the proxy records a child span and propagates
    a grandchild over ``traceparent`` so engine spans nest under the proxy."""
    monitor = get_request_stats_monitor()
    monitor.on_new_request(backend_url, request_id)
    session = await get_client_session()
    resp: Optional[web.StreamResponse] = None
    captured: list[bytes] = []
    collector = get_collector()
    proxy_ctx = trace_ctx.child() if trace_ctx is not None else None
    # Always forward X-Request-Id (router-generated when the client sent
    # none): the engine honors it (api_server req_id), so router and engine
    # logs — and trace spans — correlate on one id. Without this the engine
    # minted its own `req-...` id and the two logs never joined. Strip any
    # client-cased duplicates first — aiohttp would send both spellings.
    out_headers = {
        k: v
        for k, v in _filter_headers(request.headers).items()
        if k.lower() not in ("x-request-id", TRACEPARENT_HEADER)
    }
    out_headers["X-Request-Id"] = request_id
    if proxy_ctx is not None:
        out_headers[TRACEPARENT_HEADER] = proxy_ctx.to_traceparent()
    t_wall = time.time()
    t_route = time.perf_counter()
    proxy_attrs = {"backend": backend_url, "request_id": request_id}
    try:
        async with session.post(
            f"{backend_url}{endpoint}",
            data=body,
            headers=out_headers,
        ) as backend_resp:
            t_conn = time.perf_counter()
            resp = web.StreamResponse(
                status=backend_resp.status,
                headers={
                    **_filter_headers(backend_resp.headers),
                    "X-Request-Id": request_id,
                },
            )
            await resp.prepare(request)
            first = True
            async for chunk in backend_resp.content.iter_any():
                if first:
                    monitor.on_request_response(backend_url, request_id)
                    first = False
                    t_first = time.perf_counter()
                    record_hop_sample(
                        (t_route - (ts_recv or t_route)) * 1000,
                        (t_conn - t_route) * 1000,
                        (t_first - t_conn) * 1000,
                    )
                else:
                    monitor.on_token(backend_url, request_id)
                if capture_body is not None:
                    captured.append(chunk)
                await resp.write(chunk)
            await resp.write_eof()
            latency_hist.observe(
                time.perf_counter() - (ts_recv or t_route)
            )
            proxy_attrs["status"] = backend_resp.status
            if capture_body is not None:
                await capture_body(backend_resp.status, b"".join(captured))
            return resp
    except (aiohttp.ClientError, ConnectionResetError) as e:
        logger.error("backend %s failed for request %s: %s", backend_url, request_id, e)
        proxy_attrs["error"] = str(e)
        if resp is None or not resp.prepared:
            return web.json_response({"error": f"backend error: {e}"}, status=502)
        # headers already sent: terminate the stream instead of sending a
        # second response on the same connection
        try:
            await resp.write_eof()
        except Exception:
            pass
        return resp
    finally:
        # fires on success, backend error, AND client disconnect
        # (CancelledError). Both spans record HERE so a disconnect cannot
        # record the router.request root while dropping the router.proxy
        # span — that would orphan the engine subtree (parented under
        # proxy_ctx) out of the attribution and misattribute engine time
        # to the router
        monitor.on_request_complete(backend_url, request_id)
        collector.record(
            "router.proxy", proxy_ctx, t_wall,
            time.perf_counter() - t_route, **proxy_attrs,
        )
        if trace_ctx is not None:
            start = t_wall - ((t_route - ts_recv) if ts_recv else 0.0)
            collector.record(
                "router.request", trace_ctx, start,
                time.perf_counter() - (ts_recv or t_route),
                endpoint=endpoint, request_id=request_id,
            )


async def route_general_request(
    request: web.Request,
    endpoint: str,
    *,
    model_aliases: Optional[dict] = None,
    capture_body: Optional[object] = None,
    body_override: Optional[bytes] = None,
) -> web.StreamResponse:
    """Parse, filter endpoints by model + sleep state, route, proxy.
    Parity request.py:141-304."""
    in_router_time = time.time()
    ts_recv = time.perf_counter()
    body = body_override if body_override is not None else await request.read()
    request_id = request.headers.get("X-Request-Id") or str(uuid.uuid4())
    # request-level trace: adopt the client's traceparent (its sampled flag
    # wins — head-based sampling) or root a new trace here; every downstream
    # span (routing decision, proxy, engine phases) nests under this context.
    # child() so the router.request span has its OWN id — recording under the
    # client's span id verbatim would collide retries that reuse a header
    # into one phantom span at merge time
    trace_ctx = get_collector().root_from_headers(request.headers).child()
    try:
        request_json = json.loads(body) if body else {}
    except json.JSONDecodeError:
        return web.json_response({"error": "invalid JSON body"}, status=400)

    router = get_routing_logic()
    if isinstance(router, DisaggregatedPrefillRouter):
        return await route_disaggregated_prefill_request(
            request, endpoint, request_json, request_id,
            trace_ctx=trace_ctx, ts_recv=ts_recv,
        )

    requested_model = request_json.get("model")
    if model_aliases and requested_model in model_aliases:
        requested_model = model_aliases[requested_model]
        request_json["model"] = requested_model
        body = json.dumps(request_json).encode()

    endpoints = get_service_discovery().get_endpoint_info()
    endpoints = [ep for ep in endpoints if not ep.sleep]
    if requested_model:
        matching = [ep for ep in endpoints if requested_model in ep.model_names]
        if endpoints and not matching:
            return web.json_response(
                {"error": f"model {requested_model!r} not found"}, status=400
            )
        endpoints = matching
    if not endpoints:
        return web.json_response(
            {"error": f"no healthy endpoints for model {requested_model!r}"}, status=503
        )

    engine_stats = get_engine_stats_scraper().get_engine_stats()
    request_stats = get_request_stats_monitor().get_request_stats()
    t_route0 = time.perf_counter()
    try:
        server_url = await router.route_request(
            endpoints, engine_stats, request_stats, request, request_json
        )
    except Exception as e:
        logger.exception("routing failed")
        return web.json_response({"error": f"routing failure: {e}"}, status=500)

    curr_time = time.time()
    get_collector().record(
        "router.routing", trace_ctx.child(),
        curr_time - (time.perf_counter() - t_route0),
        time.perf_counter() - t_route0,
        backend=server_url, logic=type(router).__name__,
        request_id=request_id,
    )
    logger.info(
        "Routing request %s for model %s to %s at %f, process time = %.4f",
        request_id, requested_model, server_url, curr_time, curr_time - in_router_time,
    )
    is_streaming = bool(request_json.get("stream", False))
    return await process_request(
        request, body, server_url, endpoint, request_id,
        is_streaming=is_streaming, capture_body=capture_body, ts_recv=ts_recv,
        trace_ctx=trace_ctx,
    )


async def send_request_to_prefiller(
    session: aiohttp.ClientSession, url: str, endpoint: str, payload: dict,
    request_id: str, trace_ctx=None,
) -> dict:
    """Phase 1: run prefill with max_tokens=1 (parity request.py:307-325)."""
    headers = {"X-Request-Id": request_id}
    if trace_ctx is not None:
        headers[TRACEPARENT_HEADER] = trace_ctx.to_traceparent()
    async with session.post(
        f"{url}{endpoint}",
        json=payload,
        headers=headers,
    ) as resp:
        resp.raise_for_status()
        return await resp.json()


async def route_disaggregated_prefill_request(
    request: web.Request, endpoint: str, request_json: dict, request_id: str,
    trace_ctx=None, ts_recv: Optional[float] = None,
) -> web.StreamResponse:
    """Two-phase P/D flow (parity request.py:347-439): prefill pool computes
    KV (max_tokens=1), KV ships prefill->decode out-of-band (ICI/DCN via the
    engine's kv-transfer role), then the decode pool streams tokens."""
    router = get_routing_logic()
    assert isinstance(router, DisaggregatedPrefillRouter)
    endpoints = [ep for ep in get_service_discovery().get_endpoint_info() if not ep.sleep]
    if not endpoints:
        return web.json_response({"error": "no endpoints"}, status=503)
    prefill_url = router.route_prefill(endpoints)
    decode_url = router.route_decode(endpoints)
    monitor = get_request_stats_monitor()
    session = await get_client_session()

    orig_max_tokens = request_json.get("max_tokens", 256)
    prefill_json = dict(request_json)
    prefill_json["max_tokens"] = 1
    prefill_json["stream"] = False
    prefill_json.setdefault("kv_transfer_params", {})["request_id"] = request_id

    t0 = time.time()
    monitor.on_new_request(prefill_url, request_id)
    logger.info(
        "Routing request %s for model %s to prefill=%s decode=%s at %f",
        request_id, request_json.get("model"), prefill_url, decode_url, t0,
    )
    prefill_ctx = trace_ctx.child() if trace_ctx is not None else None
    try:
        await send_request_to_prefiller(
            session, prefill_url, endpoint, prefill_json, request_id,
            trace_ctx=prefill_ctx,
        )
        monitor.on_request_response(prefill_url, request_id)
        monitor.on_request_complete(prefill_url, request_id)
        logger.info("Prefill of %s done in %.3fs (TTFT)", request_id, time.time() - t0)
        get_collector().record(
            "router.disagg_prefill", prefill_ctx, t0, time.time() - t0,
            backend=prefill_url, request_id=request_id,
        )
    except aiohttp.ClientError as e:
        monitor.on_request_complete(prefill_url, request_id)
        return web.json_response({"error": f"prefill failed: {e}"}, status=502)

    decode_json = dict(request_json)
    decode_json["max_tokens"] = orig_max_tokens
    decode_json.setdefault("kv_transfer_params", {})["request_id"] = request_id
    body = json.dumps(decode_json).encode()
    # ts_recv rides through so the router.request root span covers the WHOLE
    # P/D request (prefill phase included) — without it the root would start
    # at the decode proxy and the disagg_prefill child would fall outside
    # its parent's window, corrupting the attribution table
    return await process_request(
        request, body, decode_url, endpoint, request_id,
        is_streaming=bool(request_json.get("stream", False)),
        trace_ctx=trace_ctx, ts_recv=ts_recv,
    )


async def route_sleep_wakeup_request(
    request: web.Request, path: str
) -> web.Response:
    """Proxy /sleep, /wake_up, /is_sleeping to a specific engine chosen by
    ?url=... or model, and update discovery sleep flags
    (parity request.py:442-514)."""
    target = request.query.get("url")
    sd = get_service_discovery()
    candidates = [ep for ep in sd.get_endpoint_info() if target is None or ep.url == target]
    # sleeping endpoints are filtered from get_endpoint_info (k8s mode) but
    # must still be reachable for wake_up
    if hasattr(sd, "endpoints"):
        known = {c.url for c in candidates}
        for ep in getattr(sd, "endpoints").values():
            if ep.url not in known and (target is None or ep.url == target):
                candidates.append(ep)
    elif target is not None and not candidates and target in getattr(sd, "urls", []):
        candidates = [EndpointInfo(url=target, model_names=[], added_timestamp=0)]
    if not candidates:
        return web.json_response({"error": "no matching engine"}, status=404)
    ep = candidates[0]
    session = await get_client_session()
    try:
        if path == "/is_sleeping":
            async with session.get(f"{ep.url}{path}") as resp:
                return web.json_response(await resp.json(), status=resp.status)
        async with session.post(
            f"{ep.url}{path}", params={k: v for k, v in request.query.items() if k != "url"}
        ) as resp:
            status = resp.status
        if status == 200:
            await sd.set_sleep_label(ep.url, path == "/sleep")
        return web.Response(status=status)
    except aiohttp.ClientError as e:
        return web.json_response({"error": str(e)}, status=502)
