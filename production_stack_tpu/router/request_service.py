"""Request proxying: the router's data plane.

Parity: src/vllm_router/services/request_service/request.py in /root/reference —
process_request (streaming proxy + stats hooks) :54-138, route_general_request
(discovery, alias/sleep filtering, routing, response headers) :141-304,
disaggregated prefill two-phase flow :307-439, sleep/wake proxying :442-514.

The "Routing request <id> ... to <url> at <t>" log line format is load-bearing:
the reference's e2e tests assert on it (tests/e2e/test-routing.py) and ours do
too (SURVEY.md §4.3).
"""

from __future__ import annotations

import asyncio
import collections
import json
import time
import uuid
from typing import Awaitable, Callable, Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.router.resilience import (
    count_batch_deprioritized,
    count_deadline_abort,
    count_failover,
    count_request_class,
    count_retry,
    count_shed,
    get_breaker_registry,
    get_retry_policy,
    get_saturation_registry,
)
from production_stack_tpu.router.routing_logic import (
    DisaggregatedPrefillRouter,
    SessionRouter,
    get_routing_logic,
)
from production_stack_tpu.router.engine_stats import get_engine_stats_scraper
from production_stack_tpu.router.request_stats import get_request_stats_monitor
from production_stack_tpu.router.service_discovery import EndpointInfo, get_service_discovery
from production_stack_tpu.tracing import TRACEPARENT_HEADER, get_collector
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

_client_session: Optional[aiohttp.ClientSession] = None

# Per-request TTFT hop samples, (recv->route, route->backend-headers,
# backend-headers->first-chunk) in ms. /metrics exposes p50/p99 per hop so
# tail latency is attributable to a stage, not just "the stack".
_hop_samples: collections.deque = collections.deque(maxlen=2048)

# Router-observed TTFT / e2e latency distributions (reference dashboard's
# heatmap panels; vLLM-compatible names + buckets — utils/metrics.py)
from production_stack_tpu.utils.metrics import (  # noqa: E402
    LATENCY_BUCKETS,
    TTFT_BUCKETS,
    Histogram,
)

ttft_hist = Histogram(
    # vllm_router: namespace, NOT vllm: — a Prometheus scraping both router
    # and engine would otherwise double-count every request in the
    # dashboard's distribution heatmaps (each request is observed once by
    # each server under the same series name)
    "vllm_router:time_to_first_token_seconds", TTFT_BUCKETS,
    "Time to first token distribution (router-observed)",
)
latency_hist = Histogram(
    "vllm_router:e2e_request_latency_seconds", LATENCY_BUCKETS,
    "End-to-end request latency distribution (router-observed)",
)


def record_hop_sample(recv_to_route: float, route_to_connect: float,
                      connect_to_first: float,
                      ttft_s: Optional[float] = None) -> list:
    """Append a TTFT hop sample and return it. The 4th slot is the request's
    final outcome, tagged at proxy completion — a sample is recorded when the
    first chunk arrives, but the stream may die later, and trace attribution
    must distinguish completed from truncated streams.

    ``ttft_s`` is the CLIENT-experienced TTFT for the histogram when it
    differs from the hop sum: a failed-over request's hops describe the
    successful attempt's stages, but its TTFT must still include the failed
    attempts and backoff the client actually waited through."""
    sample = [recv_to_route, route_to_connect, connect_to_first, "in_flight"]
    _hop_samples.append(sample)
    if ttft_s is None:
        ttft_s = (recv_to_route + route_to_connect + connect_to_first) / 1000
    ttft_hist.observe(ttft_s)
    return sample


def reset_hop_samples() -> None:
    """Clear the hop sample window (POST /metrics/reset): a benchmark phase
    scrapes then resets, so each phase's quantiles describe THAT phase's
    requests instead of pooling across differently-loaded phases."""
    _hop_samples.clear()
    ttft_hist.reset()
    latency_hist.reset()


def get_hop_quantiles() -> dict:
    """{hop: {p50, p99}} in ms over the sample window (the trailing outcome
    tag is not a timing column)."""
    if not _hop_samples:
        return {}
    cols = list(zip(*_hop_samples))[:3]
    names = ("recv_to_route", "route_to_connect", "connect_to_first_chunk")
    out = {}
    for name, vals in zip(names, cols):
        s = sorted(vals)
        out[name] = {
            "p50": s[len(s) // 2],
            "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
        }
    return out


async def get_client_session() -> aiohttp.ClientSession:
    """Shared connection-pooled client (parity: httpx_client.py)."""
    global _client_session
    if _client_session is None or _client_session.closed:
        _client_session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=10),
            connector=aiohttp.TCPConnector(limit=0),
        )
    return _client_session


async def close_client_session() -> None:
    global _client_session
    # in-flight fire-and-forget aborts would otherwise resurrect the session
    # after close (abort_on_engine re-enters get_client_session)
    for task in list(_abort_tasks):
        task.cancel()
    if _client_session and not _client_session.closed:
        await _client_session.close()
    _client_session = None


def _filter_headers(headers) -> dict:
    hop = {"host", "content-length", "transfer-encoding", "connection"}
    return {k: v for k, v in headers.items() if k.lower() not in hop}


class _RetryableProxyError(Exception):
    """Connect-stage or pre-first-byte failure: no response bytes have
    reached the client, so the request can safely fail over to another
    backend. Mid-stream failures are NOT retryable — tokens already left.

    ``status == 429`` marks a load SHED (engine admission control): the
    backend is healthy but out of capacity, so failover is immediate (no
    backoff), the circuit breaker is never fed, and ``retry_after`` carries
    the backend's Retry-After hint for the terminal client response."""

    def __init__(self, reason: str, status: int = 502,
                 retry_after: Optional[float] = None):
        super().__init__(reason)
        self.reason = reason
        self.status = status
        self.retry_after = retry_after

    @property
    def is_shed(self) -> bool:
        return self.status == 429


# longest a single 429 may exclude a backend from routing: a malformed or
# hostile Retry-After ('inf', '1e18') must never quarantine a healthy
# backend until router restart
# class-aware placement threshold (--batch-avoid-attainment; app.py sets it
# at startup, 0 disables): batch requests avoid backends whose interactive
# TTFT attainment fell below this ratio
_batch_avoid_attainment = 0.9


def set_batch_avoid_attainment(value: float) -> None:
    global _batch_avoid_attainment
    _batch_avoid_attainment = max(0.0, float(value))


def request_priority(headers, body_json: Optional[dict]) -> str:
    """Resolve a request's SLO class: ``X-Priority`` header wins, then a
    ``priority`` body field; anything outside the closed {interactive,
    batch} set (and the unlabeled default) degrades to interactive — the
    protective class — so a typo never silently deprioritizes a tenant."""
    raw = None
    if headers is not None:
        raw = headers.get("X-Priority")
    if not raw and body_json:
        raw = body_json.get("priority")
    pri = str(raw or "interactive").strip().lower()
    return pri if pri in ("interactive", "batch") else "interactive"


MAX_RETRY_AFTER_S = 60.0


def _parse_retry_after(raw: Optional[str]) -> float:
    """Retry-After header seconds (delta form only; HTTP-date is overkill
    for an intra-cluster contract). Malformed/absent -> 1 s; clamped to
    [0, MAX_RETRY_AFTER_S]."""
    try:
        v = float(raw)
    except (TypeError, ValueError):
        return 1.0
    if v != v:  # NaN
        return 1.0
    return min(MAX_RETRY_AFTER_S, max(0.0, v))


def _overloaded_response(message: str, retry_after: Optional[float]) -> web.Response:
    """Terminal 429 + Retry-After for a fleet-wide shed (mirrors the
    engine's shed contract, api_server._shed_response): the honest answer
    under saturation, and the signal well-behaved clients back off on."""
    retry = max(1, int(-(-(retry_after or 1.0) // 1)))  # ceil, floor 1 s
    return web.json_response(
        {"error": {"message": message, "type": "overloaded_error",
                   "code": 429}},
        status=429,
        headers={"Retry-After": str(retry)},
    )


async def abort_on_engine(backend_url: str, request_id: str) -> None:
    """Best-effort engine-side abort (POST /abort): closing the proxy's TCP
    connection only reaches a backend that is actively writing — a HUNG
    engine would keep the scheduler slot and KV pages pinned forever. The
    call is fire-and-forget: a plain-vLLM pod without /abort, or a dead pod,
    must not add latency to the abort path."""
    try:
        session = await get_client_session()
        async with session.post(
            f"{backend_url}/abort",
            json={"request_id": request_id},
            timeout=aiohttp.ClientTimeout(total=2),
        ):
            pass
    except Exception:  # noqa: BLE001 - abort is advisory
        pass


# strong refs for fire-and-forget abort tasks (a bare create_task could be
# garbage-collected mid-flight); drained on close_client_session
_abort_tasks: set = set()  # owned-by: event-loop


def spawn_abort(backend_url: str, request_id: str) -> "asyncio.Task":
    """Fire-and-forget engine-side abort: the reclaim must not serialize into
    the request path (a partitioned pod would add the abort call's full 2s
    budget to every failover and delay the client's SSE error event). The
    returned task is tracked so close_client_session can cancel stragglers
    instead of letting them resurrect the shared session after close."""
    task = asyncio.get_running_loop().create_task(
        abort_on_engine(backend_url, request_id)
    )
    _abort_tasks.add(task)
    task.add_done_callback(_abort_tasks.discard)
    return task


# live-migration stream handoff (docs/migration.md): a migrating source
# engine ends its SSE leg with ONE control event instead of [DONE]; the
# proxy suppresses the event, attaches to the target's /migrate_attach, and
# splices the continuation into the client's still-open response — the
# client sees one uninterrupted stream.
MIGRATION_MARKER = b'data: {"pstpu_migration"'


async def _read_migration_event(chunk: bytes, chunks):
    """Split ``chunk`` at the migration control event.

    Returns ``(forward_bytes, event_dict | None)``. The event is the
    stream's final event, but TCP may fragment it across reads — keep
    pulling until its ``\\n\\n`` terminator. A torn or unparseable event is
    treated as absent and forwarded verbatim (the client then sees the raw
    event, which is still better than eating its bytes)."""
    idx = chunk.rfind(MIGRATION_MARKER)
    if idx < 0:
        return chunk, None
    prefix, rest = chunk[:idx], chunk[idx:]
    while b"\n\n" not in rest:
        try:
            rest += await asyncio.wait_for(chunks.__anext__(), 5.0)
        except (StopAsyncIteration, asyncio.TimeoutError, aiohttp.ClientError,
                ConnectionResetError):
            return prefix + rest, None
    payload = rest[len(b"data: "): rest.find(b"\n\n")]
    try:
        event = json.loads(payload)["pstpu_migration"]
    except (ValueError, KeyError, TypeError):
        return prefix + rest, None
    if not isinstance(event, dict):
        return prefix + rest, None
    return prefix, event


def _marker_tail_overlap(chunk: bytes) -> int:
    """Length of the longest suffix of ``chunk`` that is a proper prefix of
    the migration marker. TCP may split the source's final write ANYWHERE —
    including inside the marker itself — and a marker split across two reads
    would otherwise leak the raw control event to the client and skip the
    splice. The proxy withholds such a tail (<= 23 bytes) until the next
    read resolves it."""
    for k in range(min(len(MIGRATION_MARKER) - 1, len(chunk)), 0, -1):
        if chunk.endswith(MIGRATION_MARKER[:k]):
            return k
    return 0


def _maybe_pin_session(request, target: str) -> None:
    """SessionRouter re-pin: the hash ring is deterministic, so without an
    explicit pin the session's NEXT request would route straight back to the
    backend the controller just migrated it off."""
    from production_stack_tpu.router.resilience import get_session_pins

    try:
        router = get_routing_logic()
    except AssertionError:  # embedded/unit use without initialized routing
        return
    if not isinstance(router, SessionRouter):
        return
    headers = getattr(request, "headers", None)
    sid = headers.get(router.session_key) if headers is not None else None
    if sid:
        get_session_pins().pin(str(sid), target)


async def _splice_migrated_stream(
    resp: web.StreamResponse,
    event: dict,
    *,
    request: web.Request,
    session: aiohttp.ClientSession,
    stall_timeout: Optional[float],
    breakers,
    captured: Optional[list] = None,
) -> bool:
    """Attach to the migration target and splice the continuation into the
    client's open response. Loops: a continuation may itself migrate again
    (chained handoff — e.g. its new home drains too), ending its leg with
    another control event. Returns True when [DONE] reached the client;
    on failure the client gets the terminal SSE error event (PR 2 contract)
    — tokens already streamed, so failover is no longer possible."""
    from production_stack_tpu.router.resilience import (
        count_migration_splice_failure,
        count_session_repin,
    )

    hops = 0
    while event is not None:
        if hops >= 8:  # chained-handoff loop bound
            # the cap firing means a pathological migration loop — the
            # stream must end with the explicit error contract, never a
            # silent truncation recorded as success
            count_migration_splice_failure()
            logger.error(
                "migration splice exceeded %d chained hops for %s; aborting",
                hops, event.get("request_id"),
            )
            await resp.write(_sse_error_event(
                f"migration handoff chain exceeded {hops} hops", 502
            ))
            return False
        hops += 1
        target = str(event.get("target") or "").rstrip("/")
        mig_id = event.get("request_id")
        next_event = None
        try:
            if not target or not mig_id:
                raise ValueError(f"malformed migration event: {event}")
            async with session.post(
                f"{target}/migrate_attach", json={"request_id": mig_id},
                timeout=aiohttp.ClientTimeout(total=None, sock_connect=10),
            ) as tr:
                if tr.status != 200:
                    detail = (await tr.read())[:200]
                    raise ValueError(
                        f"attach returned {tr.status}: "
                        f"{detail.decode(errors='replace')}"
                    )
                # the splice IS the session re-pin: count it and pin the
                # session key (when a SessionRouter is active) to the target
                count_session_repin()
                _maybe_pin_session(request, target)
                chunks = tr.content.iter_any()
                while True:
                    try:
                        if stall_timeout:
                            chunk = await asyncio.wait_for(
                                chunks.__anext__(), stall_timeout
                            )
                        else:
                            chunk = await chunks.__anext__()
                    except StopAsyncIteration:
                        break
                    chunk, next_event = await _read_migration_event(
                        chunk, chunks
                    )
                    if chunk:
                        if captured is not None:
                            captured.append(chunk)
                        await resp.write(chunk)
                    if next_event is not None:
                        break
        except (aiohttp.ClientError, ConnectionResetError,
                asyncio.TimeoutError, OSError, ValueError) as e:
            if target:
                breakers.record_failure(target)
            count_migration_splice_failure()
            logger.error(
                "migration splice to %s failed for %s: %s", target, mig_id, e
            )
            await resp.write(_sse_error_event(
                f"migration handoff to {target or '?'} failed: {e}", 502
            ))
            return False
        event = next_event
    return True


def _sse_error_event(message: str, code: int = 502) -> bytes:
    """Terminal SSE error event (docs/failure-handling.md contract): a
    mid-stream backend death must surface as an explicit `error` payload, not
    a silently truncated 200. No [DONE] follows — its absence is how clients
    distinguish an errored stream from a clean EOF. The leading blank line
    forces an event boundary: the connection may have died MID-chunk, and
    gluing this onto a partial `data:` line would make both unparseable."""
    payload = {"error": {"message": message, "type": "upstream_error", "code": code}}
    return f"\n\ndata: {json.dumps(payload)}\n\n".encode()


async def process_request(
    request: web.Request,
    body: bytes,
    backend_url: str,
    endpoint: str,
    request_id: str,
    *,
    is_streaming: bool,
    capture_body: Optional[object] = None,
    ts_recv: Optional[float] = None,
    trace_ctx=None,
    pick_next: Optional[Callable[[set], Awaitable[Optional[str]]]] = None,
    attempts_anchor: Optional[float] = None,
) -> web.StreamResponse:
    """Proxy `body` to backend and stream the response back, firing request
    stats callbacks (parity request.py:54-138), with the failure-domain layer
    wrapped around the attempt: connect-stage and pre-first-byte failures
    retry with capped backoff against ``pick_next``'s next-choice endpoint
    (excluding already-failed URLs), bounded by the attempt budget and the
    per-request deadline; every outcome feeds the backend's circuit breaker.

    `capture_body(status, bytes)` — optional async callback fired with the full
    response once the proxy completes (semantic-cache store, post_request
    callbacks). ``ts_recv`` is the perf_counter when the router first saw the
    request, for the per-hop TTFT breakdown. ``trace_ctx`` is the router's
    request-level span context; each attempt records a child span and
    propagates a grandchild over ``traceparent`` so engine spans nest under
    the attempt that actually served them."""
    policy = get_retry_policy()
    breakers = get_breaker_registry()
    collector = get_collector()
    # ``attempts_anchor`` lets a two-phase caller (disaggregated prefill)
    # charge its phase-1 time against the same --deadline-request budget
    # instead of granting the decode phase a fresh clock
    t_attempts0 = attempts_anchor if attempts_anchor is not None else time.monotonic()
    t_wall0 = time.time()
    t_perf0 = time.perf_counter()
    attempt = 0
    tried: set[str] = set()
    last_err: Optional[_RetryableProxyError] = None
    try:
        while True:
            attempt += 1
            tried.add(backend_url)
            # retries forward an attempt-suffixed id: attempt 1's sequence may
            # still be live on the engine (the abort is best-effort), and two
            # live sequences with one seq_id would cross-wire their output
            # queues. The client-visible X-Request-Id stays the original.
            wire_id = request_id if attempt == 1 else f"{request_id}#r{attempt}"
            try:
                return await _proxy_attempt(
                    request, body, backend_url, endpoint, request_id,
                    wire_id=wire_id,
                    attempt=attempt, capture_body=capture_body,
                    ts_recv=ts_recv, trace_ctx=trace_ctx,
                    policy=policy, breakers=breakers, t_attempts0=t_attempts0,
                )
            except _RetryableProxyError as e:
                last_err = e
                if e.is_shed:
                    # engine load shed (429 + Retry-After): the backend is
                    # healthy, just out of capacity — NEVER feeds the
                    # breaker (acceptance: shed failover must not trip it)
                    logger.warning(
                        "backend %s shed request %s (attempt %d/%d, "
                        "retry-after %.1fs); failing over",
                        backend_url, request_id, attempt, policy.max_attempts,
                        e.retry_after or 1.0,
                    )
                else:
                    breakers.record_failure(backend_url)
                    logger.error(
                        "backend %s failed for request %s (attempt %d/%d): %s",
                        backend_url, request_id, attempt, policy.max_attempts,
                        e.reason,
                    )
                    # replay dedupe: the failed attempt may still be EXECUTING
                    # on its engine (a snapped TCP connection with no bytes in
                    # flight goes unnoticed by a non-streaming generation, and
                    # the engine would run it to completion while the replay
                    # runs elsewhere — double execution fleet-wide). Abort it
                    # by the attempt's echoed X-Request-Id (wire_id) before
                    # failing over; unknown/finished ids are engine-side
                    # no-ops, and the deadline paths' own aborts make this
                    # idempotent. Sheds skip it: a shed was never admitted.
                    spawn_abort(backend_url, wire_id)
            remaining = policy.remaining(t_attempts0)
            if remaining is not None and remaining <= 0:
                count_deadline_abort("request")
                return web.json_response(
                    {"error": f"request deadline exceeded after {attempt} "
                              f"attempt(s): {last_err.reason}"},
                    status=504,
                )
            if attempt >= policy.max_attempts:
                break
            nxt = None
            if pick_next is not None:
                try:
                    nxt = await pick_next(tried)
                except Exception:
                    logger.exception("failover routing failed")
            if nxt is None:
                if last_err.is_shed:
                    # every alternative is saturated too: surface the 429 +
                    # Retry-After now — re-queueing on a known-saturated
                    # backend only adds latency to an honest answer
                    break
                # no alternative endpoint: re-try the same backend only if
                # its breaker still admits traffic, else give up now
                if not breakers.allows(backend_url):
                    break
                nxt = backend_url
            # shed failover is IMMEDIATE: the engine told us exactly why it
            # refused, and other engines have capacity — backoff only delays
            # the client while the shedding engine's queue drains
            delay = 0.0 if last_err.is_shed else policy.backoff(attempt)
            if remaining is not None:
                delay = min(delay, max(0.0, remaining))
            count_retry()
            if nxt != backend_url:
                count_failover()
                logger.warning(
                    "failing request %s over: %s -> %s (attempt %d, backoff %.0f ms)",
                    request_id, backend_url, nxt, attempt + 1, delay * 1000,
                )
            await asyncio.sleep(delay)
            backend_url = nxt
        if last_err.is_shed:
            # all candidates saturated: forward the shed verbatim
            return _overloaded_response(
                f"all backends saturated after {attempt} attempt(s): "
                f"{last_err.reason}",
                last_err.retry_after,
            )
        return web.json_response(
            {"error": f"backend error after {attempt} attempt(s): {last_err.reason}"},
            status=last_err.status if last_err.status >= 500 else 502,
        )
    finally:
        # fires on success, backend error, AND client disconnect
        # (CancelledError): the router.request root span must record exactly
        # once per request regardless of how many proxy attempts ran
        if trace_ctx is not None:
            start = t_wall0 - ((t_perf0 - ts_recv) if ts_recv else 0.0)
            collector.record(
                "router.request", trace_ctx, start,
                time.perf_counter() - (ts_recv or t_perf0),
                endpoint=endpoint, request_id=request_id, attempts=attempt,
            )


async def _proxy_attempt(
    request: web.Request,
    body: bytes,
    backend_url: str,
    endpoint: str,
    request_id: str,
    *,
    wire_id: Optional[str] = None,
    attempt: int,
    capture_body,
    ts_recv,
    trace_ctx,
    policy,
    breakers,
    t_attempts0: float,
) -> web.StreamResponse:
    """One proxy attempt. Raises _RetryableProxyError while failover is still
    possible (nothing sent to the client); after the response is committed,
    failures terminate the stream with the SSE error-event contract."""
    monitor = get_request_stats_monitor()
    session = await get_client_session()
    collector = get_collector()
    wire_id = wire_id or request_id
    proxy_ctx = trace_ctx.child() if trace_ctx is not None else None
    # Always forward X-Request-Id (router-generated when the client sent
    # none): the engine honors it (api_server req_id), so router and engine
    # logs — and trace spans — correlate on one id. Without this the engine
    # minted its own `req-...` id and the two logs never joined. Strip any
    # client-cased duplicates first — aiohttp would send both spellings.
    out_headers = {
        k: v
        for k, v in _filter_headers(request.headers).items()
        if k.lower() not in ("x-request-id", TRACEPARENT_HEADER)
    }
    out_headers["X-Request-Id"] = wire_id
    if proxy_ctx is not None:
        out_headers[TRACEPARENT_HEADER] = proxy_ctx.to_traceparent()
    t_wall = time.time()
    t_route = time.perf_counter()
    proxy_attrs = {"backend": backend_url, "request_id": request_id,
                   "attempt": attempt}
    if wire_id != request_id:
        proxy_attrs["wire_id"] = wire_id  # engine-side id for this attempt
    outcome = "error"
    hop_sample: Optional[list] = None
    backend_resp: Optional[aiohttp.ClientResponse] = None
    resp: Optional[web.StreamResponse] = None
    monitor.on_new_request(backend_url, request_id)

    # pre-first-byte budget: TTFT deadline, clamped by what's left of the
    # per-request (attempt-phase) deadline
    ttft_deadline_at: Optional[float] = None
    if policy.deadline_ttft > 0:
        ttft_deadline_at = time.monotonic() + policy.deadline_ttft
    remaining = policy.remaining(t_attempts0)
    if remaining is not None:
        at = time.monotonic() + max(0.0, remaining)
        ttft_deadline_at = min(ttft_deadline_at, at) if ttft_deadline_at else at

    async def _bounded(awaitable, *, kind: str):
        """Await within the pre-first-byte deadline; deadline expiry aborts
        the engine-side request and converts to a retryable failure."""
        if ttft_deadline_at is None:
            return await awaitable
        budget = ttft_deadline_at - time.monotonic()
        try:
            return await asyncio.wait_for(awaitable, max(budget, 0.001))
        except asyncio.TimeoutError:
            count_deadline_abort(kind)
            spawn_abort(backend_url, wire_id)
            raise _RetryableProxyError(
                f"no first byte from {backend_url} within deadline "
                f"({kind})", 504,
            ) from None

    try:
        # ---- retryable stage: connect + headers + first chunk -------------
        try:
            backend_resp = await _bounded(
                session.post(f"{backend_url}{endpoint}", data=body,
                             headers=out_headers),
                kind="ttft",
            )
        except (aiohttp.ClientError, ConnectionResetError, OSError) as e:
            raise _RetryableProxyError(f"connect failed: {e}") from e
        t_conn = time.perf_counter()
        if backend_resp.status >= 500:
            # a 5xx body is small and already formed; drain it (bounded — a
            # backend that hangs after its error headers must not pin us)
            try:
                detail = (await asyncio.wait_for(backend_resp.read(), 2.0))[:200]
            except Exception:  # noqa: BLE001 - body is best-effort detail
                detail = b""
            raise _RetryableProxyError(
                f"backend returned {backend_resp.status}: "
                f"{detail.decode(errors='replace')}",
                backend_resp.status,
            )
        if backend_resp.status == 429:
            # engine load shed (admission control): remember the Retry-After
            # window so routing stops offering this backend new traffic, and
            # convert to an immediate breaker-neutral failover
            retry_after = _parse_retry_after(
                backend_resp.headers.get("Retry-After")
            )
            try:
                detail = (await asyncio.wait_for(backend_resp.read(), 2.0))[:200]
            except Exception:  # noqa: BLE001 - body is best-effort detail
                detail = b""
            get_saturation_registry().mark(backend_url, retry_after)
            count_shed()
            raise _RetryableProxyError(
                f"backend shed the request (429, retry-after {retry_after:g}s): "
                f"{detail.decode(errors='replace')}",
                429, retry_after=retry_after,
            )
        chunks = backend_resp.content.iter_any()
        first_chunk: Optional[bytes] = None
        try:
            first_chunk = await _bounded(chunks.__anext__(), kind="ttft")
        except StopAsyncIteration:
            pass  # empty body (204s, HEAD-ish replies): still a success
        except (aiohttp.ClientError, ConnectionResetError) as e:
            raise _RetryableProxyError(f"died before first byte: {e}") from e

        # ---- committed stage: headers are sent, no more failover ----------
        resp = web.StreamResponse(
            status=backend_resp.status,
            headers={
                **_filter_headers(backend_resp.headers),
                "X-Request-Id": request_id,
            },
        )
        await resp.prepare(request)
        is_sse = "text/event-stream" in (
            backend_resp.headers.get("Content-Type") or ""
        )
        stall_timeout = policy.deadline_inter_chunk or None
        captured: list[bytes] = []
        first = True
        chunk = first_chunk
        mig_carry = b""  # withheld possible-marker-prefix tail (SSE only)
        while chunk is not None:
            mig_event = None
            if is_sse:
                if mig_carry:
                    chunk = mig_carry + chunk
                    mig_carry = b""
                if MIGRATION_MARKER in chunk:
                    # live-migration handoff: split out the control event
                    # (it must never reach the client) before forwarding
                    chunk, mig_event = await _read_migration_event(
                        chunk, chunks
                    )
                elif chunk:
                    # a chunk tail that could be the START of a split
                    # marker is withheld until the next read resolves it
                    k = _marker_tail_overlap(chunk)
                    if k:
                        mig_carry = chunk[-k:]
                        chunk = chunk[:-k]
            if chunk or mig_event is None:
                if first:
                    monitor.on_request_response(backend_url, request_id)
                    first = False
                    t_first = time.perf_counter()
                    # hop columns are attempt-relative (stage costs stay honest:
                    # retry/backoff time of earlier attempts must not pollute the
                    # recv_to_route quantiles); the TTFT histogram still gets the
                    # full client-experienced window including failed attempts
                    hop_sample = record_hop_sample(
                        (t_route - (ts_recv or t_route)) * 1000 if attempt == 1 else 0.0,
                        (t_conn - t_route) * 1000,
                        (t_first - t_conn) * 1000,
                        ttft_s=t_first - (ts_recv or t_route),
                    )
                else:
                    monitor.on_token(backend_url, request_id)
            if chunk:
                if capture_body is not None:
                    captured.append(chunk)
                await resp.write(chunk)
            if mig_event is not None:
                # the source leg ended cleanly by handing the stream over:
                # splice the continuation from the target into the client's
                # open response (docs/migration.md)
                spliced_ok = await _splice_migrated_stream(
                    resp, mig_event, request=request, session=session,
                    stall_timeout=stall_timeout, breakers=breakers,
                    captured=captured if capture_body is not None else None,
                )
                proxy_attrs["migrated_to"] = mig_event.get("target")
                outcome = "migrated" if spliced_ok else "migration_splice_failed"
                breakers.record_success(backend_url)
                latency_hist.observe(time.perf_counter() - (ts_recv or t_route))
                if spliced_ok and capture_body is not None:
                    await capture_body(backend_resp.status, b"".join(captured))
                try:
                    await resp.write_eof()
                except Exception:  # noqa: BLE001 - client may be gone
                    pass
                return resp
            try:
                # per-chunk wait_for costs a Task per chunk, but only when
                # the stall deadline is enabled. ClientTimeout(sock_read=…)
                # would be cheaper but ALSO bounds the pre-first-byte gap,
                # which must stay governed by the (longer) TTFT deadline —
                # a slow prefill is not a stalled stream.
                if stall_timeout:
                    chunk = await asyncio.wait_for(
                        chunks.__anext__(), stall_timeout
                    )
                else:
                    chunk = await chunks.__anext__()
            except StopAsyncIteration:
                chunk = None
            except asyncio.TimeoutError:
                # mid-stream stall: reclaim the engine slot and tell the
                # client explicitly — never leave a silently-frozen 200
                count_deadline_abort("inter_chunk")
                spawn_abort(backend_url, wire_id)
                backend_resp.close()
                breakers.record_failure(backend_url)
                outcome = "deadline_inter_chunk"
                proxy_attrs["error"] = (
                    f"stream stalled > {stall_timeout}s between chunks"
                )
                logger.error(
                    "request %s stalled on %s (> %.1fs between chunks); aborted",
                    request_id, backend_url, stall_timeout,
                )
                if is_sse:
                    await resp.write(_sse_error_event(
                        f"upstream stream stalled after {stall_timeout}s; aborted",
                        504,
                    ))
                await resp.write_eof()
                return resp
            except (aiohttp.ClientError, ConnectionResetError) as e:
                breakers.record_failure(backend_url)
                outcome = "truncated"
                proxy_attrs["error"] = str(e)
                logger.error(
                    "backend %s died mid-stream for request %s: %s",
                    backend_url, request_id, e,
                )
                if is_sse:
                    await resp.write(_sse_error_event(
                        f"upstream connection lost mid-stream: {e}", 502,
                    ))
                try:
                    await resp.write_eof()
                except Exception:  # noqa: BLE001 - client may be gone too
                    pass
                return resp
        if mig_carry:
            # clean EOF with a withheld tail: it was ordinary content that
            # merely LOOKED like a marker prefix — deliver it
            if capture_body is not None:
                captured.append(mig_carry)
            await resp.write(mig_carry)
        await resp.write_eof()
        latency_hist.observe(time.perf_counter() - (ts_recv or t_route))
        if hop_sample is None:
            # no body chunk ever arrived (204s / empty non-streaming
            # replies): the request still completed, and the engine-side
            # histograms count it — record a TTFT-equals-latency sample so
            # the router and engine /metrics distributions keep covering
            # the SAME request population (a request must never appear in
            # the router's latency histogram but not its TTFT one)
            t_done = time.perf_counter()
            hop_sample = record_hop_sample(
                (t_route - (ts_recv or t_route)) * 1000 if attempt == 1 else 0.0,
                (t_conn - t_route) * 1000,
                (t_done - t_conn) * 1000,
                ttft_s=t_done - (ts_recv or t_route),
            )
        proxy_attrs["status"] = backend_resp.status
        outcome = "ok"
        breakers.record_success(backend_url)
        if capture_body is not None:
            await capture_body(backend_resp.status, b"".join(captured))
        return resp
    except _RetryableProxyError as e:
        outcome = "shed" if e.is_shed else "retryable_error"
        if backend_resp is not None:
            backend_resp.close()
        raise
    except ConnectionResetError:
        # CLIENT went away mid-write (headers already sent): backend-side
        # resets are converted to _RetryableProxyError / truncated above, so
        # a reset here is ours. Release the backend leg and reclaim the
        # engine slot; there is nobody left to stream to.
        outcome = "client_disconnect"
        if backend_resp is not None:
            backend_resp.close()
        spawn_abort(backend_url, wire_id)
        return resp
    except asyncio.CancelledError:
        # client disconnect: close the backend leg so an actively-writing
        # engine notices; a hung one is covered by the abort call
        outcome = "client_disconnect"
        if backend_resp is not None:
            backend_resp.close()
        # shielded await over a TRACKED task: this handler is being torn
        # down, so the abort must survive our cancellation — but it must
        # also stay cancellable by close_client_session at shutdown, or it
        # could resurrect the shared session after close
        await asyncio.shield(spawn_abort(backend_url, wire_id))
        raise
    finally:
        # fires on every exit path so a disconnect cannot record the
        # router.request root while dropping this attempt's router.proxy
        # span — that would orphan the engine subtree (parented under
        # proxy_ctx) out of the attribution and misattribute engine time
        # to the router
        monitor.on_request_complete(backend_url, request_id)
        proxy_attrs["outcome"] = outcome
        if hop_sample is not None:
            hop_sample[3] = outcome
        collector.record(
            "router.proxy", proxy_ctx, t_wall,
            time.perf_counter() - t_route, **proxy_attrs,
        )


async def route_general_request(
    request: web.Request,
    endpoint: str,
    *,
    model_aliases: Optional[dict] = None,
    capture_body: Optional[object] = None,
    body_override: Optional[bytes] = None,
) -> web.StreamResponse:
    """Parse, filter endpoints by model + sleep state, route, proxy.
    Parity request.py:141-304."""
    in_router_time = time.time()
    ts_recv = time.perf_counter()
    body = body_override if body_override is not None else await request.read()
    request_id = request.headers.get("X-Request-Id") or str(uuid.uuid4())
    # request-level trace: adopt the client's traceparent (its sampled flag
    # wins — head-based sampling) or root a new trace here; every downstream
    # span (routing decision, proxy, engine phases) nests under this context.
    # child() so the router.request span has its OWN id — recording under the
    # client's span id verbatim would collide retries that reuse a header
    # into one phantom span at merge time
    trace_ctx = get_collector().root_from_headers(request.headers).child()
    try:
        request_json = json.loads(body) if body else {}
    except json.JSONDecodeError:
        return web.json_response({"error": "invalid JSON body"}, status=400)

    router = get_routing_logic()
    if isinstance(router, DisaggregatedPrefillRouter):
        return await route_disaggregated_prefill_request(
            request, endpoint, request_json, request_id,
            trace_ctx=trace_ctx, ts_recv=ts_recv,
        )

    requested_model = request_json.get("model")
    if model_aliases and requested_model in model_aliases:
        requested_model = model_aliases[requested_model]
        request_json["model"] = requested_model
        body = json.dumps(request_json).encode()

    endpoints = get_service_discovery().get_endpoint_info()
    endpoints = [ep for ep in endpoints if not ep.sleep]
    if requested_model:
        matching = [ep for ep in endpoints if requested_model in ep.model_names]
        if endpoints and not matching:
            return web.json_response(
                {"error": f"model {requested_model!r} not found"}, status=400
            )
        endpoints = matching
    if not endpoints:
        return web.json_response(
            {"error": f"no healthy endpoints for model {requested_model!r}"}, status=503
        )

    # passive circuit breaking: open-breaker backends drop out of the
    # candidate set (fail-static: an all-open set passes through unchanged,
    # so a fully-tripped fleet degrades to "try anyway", never a hard 503)
    candidates = endpoints
    endpoints = get_breaker_registry().filter_endpoints(endpoints)

    engine_stats = get_engine_stats_scraper().get_engine_stats()
    # shed-aware placement: saturated backends (inside a 429 Retry-After
    # window, or reporting vllm:engine_saturated on scrape) receive no new
    # NON-STICKY traffic. Sticky means THIS request actually resolves a
    # session key — that request keeps its backend (losing affinity costs a
    # full-prefix recompute; the engine's own 429 plus failover covers the
    # truly-saturated case). Keyless requests under a SessionRouter fall
    # back to QPS routing and are as re-homeable as any other traffic, so
    # they route around saturation too. Fail-static when the whole fleet is
    # saturated.
    sticky = False
    if isinstance(router, SessionRouter):
        headers = getattr(request, "headers", None)
        sticky = bool(
            (headers.get(router.session_key) if headers is not None else None)
            or (request_json or {}).get(router.session_key)
        )
    # SLO-class tagging (docs/failure-handling.md priority classes): resolve
    # the class once here; _filter_headers forwards X-Priority to the engine
    # untouched, so the engine's class-aware admission sees the same label.
    priority = request_priority(getattr(request, "headers", None), request_json)
    count_request_class(priority)
    if not sticky:
        endpoints = router.saturation_filtered(endpoints, engine_stats)
        # batch avoids engines failing their interactive tenants (fail-static
        # inside class_filtered — a fully-degraded fleet passes through and
        # the engine's batch-first shed answers with the honest 429)
        filtered = router.class_filtered(
            endpoints, priority, _batch_avoid_attainment
        )
        if len(filtered) < len(endpoints):
            count_batch_deprioritized()
        endpoints = filtered

    request_stats = get_request_stats_monitor().get_request_stats()
    t_route0 = time.perf_counter()
    try:
        server_url = await router.route_request(
            endpoints, engine_stats, request_stats, request, request_json
        )
    except Exception as e:
        logger.exception("routing failed")
        return web.json_response({"error": f"routing failure: {e}"}, status=500)

    async def pick_next(excluded: set) -> Optional[str]:
        """Failover target: re-run the routing logic over the surviving
        candidates (already-failed URLs excluded, open breakers and
        saturated backends excluded WITHOUT the fail-static fallback — if
        every alternative is tripped or shedding, surfacing the original
        error/429 beats queueing on a known-bad or known-full pod)."""
        sat = get_saturation_registry()
        pool = [
            ep for ep in candidates
            if ep.url not in excluded and not sat.is_saturated(ep.url)
        ]
        pool = get_breaker_registry().filter_endpoints(pool, fail_static=False)
        if not pool:
            return None
        return await router.route_request(
            pool,
            get_engine_stats_scraper().get_engine_stats(),
            get_request_stats_monitor().get_request_stats(),
            request, request_json,
        )

    curr_time = time.time()
    get_collector().record(
        "router.routing", trace_ctx.child(),
        curr_time - (time.perf_counter() - t_route0),
        time.perf_counter() - t_route0,
        backend=server_url, logic=type(router).__name__,
        request_id=request_id,
    )
    logger.info(
        "Routing request %s for model %s to %s at %f, process time = %.4f",
        request_id, requested_model, server_url, curr_time, curr_time - in_router_time,
    )
    is_streaming = bool(request_json.get("stream", False))
    return await process_request(
        request, body, server_url, endpoint, request_id,
        is_streaming=is_streaming, capture_body=capture_body, ts_recv=ts_recv,
        trace_ctx=trace_ctx, pick_next=pick_next,
    )


async def send_request_to_prefiller(
    session: aiohttp.ClientSession, url: str, endpoint: str, payload: dict,
    request_id: str, trace_ctx=None, timeout: Optional[float] = None,
) -> "tuple[int, dict]":
    """Phase 1: run prefill with max_tokens=1 (parity request.py:307-325).
    ``timeout`` bounds the whole phase — a hung prefiller must convert to a
    failover, not pin the request (and its KV pages) forever.

    Returns ``(status, body)`` for non-5xx responses; raises
    _RetryableProxyError for 5xx so only genuine backend failures enter the
    retry/breaker path — a 400 (client's fault) must pass through verbatim,
    not trip every healthy prefiller's breaker."""
    headers = {"X-Request-Id": request_id}
    if trace_ctx is not None:
        headers[TRACEPARENT_HEADER] = trace_ctx.to_traceparent()
    async with session.post(
        f"{url}{endpoint}",
        json=payload,
        headers=headers,
        timeout=aiohttp.ClientTimeout(total=timeout or None),
    ) as resp:
        if resp.status >= 500:
            detail = (await resp.read())[:200]
            raise _RetryableProxyError(
                f"prefiller returned {resp.status}: "
                f"{detail.decode(errors='replace')}",
                resp.status,
            )
        if resp.status == 429:
            # prefiller shed (admission control): breaker-neutral immediate
            # failover to another prefiller, same as the general proxy path
            retry_after = _parse_retry_after(resp.headers.get("Retry-After"))
            get_saturation_registry().mark(url, retry_after)
            count_shed()
            raise _RetryableProxyError(
                f"prefiller shed the request (429, retry-after "
                f"{retry_after:g}s)", 429, retry_after=retry_after,
            )
        try:
            body = await resp.json()
        except Exception:  # noqa: BLE001 - non-JSON 4xx body
            body = {"error": (await resp.text())[:500]}
        return resp.status, body


async def route_disaggregated_prefill_request(
    request: web.Request, endpoint: str, request_json: dict, request_id: str,
    trace_ctx=None, ts_recv: Optional[float] = None,
) -> web.StreamResponse:
    """Two-phase P/D flow (parity request.py:347-439): prefill pool computes
    KV (max_tokens=1), KV ships prefill->decode out-of-band (ICI/DCN via the
    engine's kv-transfer role), then the decode pool streams tokens."""
    router = get_routing_logic()
    assert isinstance(router, DisaggregatedPrefillRouter)
    endpoints = [ep for ep in get_service_discovery().get_endpoint_info() if not ep.sleep]
    if not endpoints:
        return web.json_response({"error": "no endpoints"}, status=503)
    policy = get_retry_policy()
    breakers = get_breaker_registry()
    # no set-wide pre-filter here: route_prefill/route_decode breaker-filter
    # per ROLE internally, so a tripped prefiller degrades fail-static within
    # the prefill pool instead of re-homing prefill onto decode pods
    prefill_url = router.route_prefill(endpoints)
    decode_url = router.route_decode(endpoints)
    monitor = get_request_stats_monitor()
    session = await get_client_session()

    orig_max_tokens = request_json.get("max_tokens", 256)
    prefill_json = dict(request_json)
    prefill_json["max_tokens"] = 1
    prefill_json["stream"] = False
    prefill_json.setdefault("kv_transfer_params", {})["request_id"] = request_id

    t_start = time.time()
    t_attempts0 = time.monotonic()  # --deadline-request anchor, both phases
    logger.info(
        "Routing request %s for model %s to prefill=%s decode=%s at %f",
        request_id, request_json.get("model"), prefill_url, decode_url, t_start,
    )

    def _phase_timeout() -> Optional[float]:
        """Per-attempt prefill timeout: the TTFT deadline clamped by what is
        left of the per-request (attempt-phase) deadline."""
        t = policy.deadline_ttft if policy.deadline_ttft > 0 else None
        rem = policy.remaining(t_attempts0)
        if rem is not None:
            t = min(t, max(rem, 0.001)) if t else max(rem, 0.001)
        return t

    # phase-1 failover: a failed/hung prefiller retries against another
    # prefiller (already-failed URLs excluded), same budget/backoff/deadline
    # as the general proxy path
    attempt = 0
    tried: set = set()
    while True:
        attempt += 1
        tried.add(prefill_url)
        t0 = time.time()
        monitor.on_new_request(prefill_url, request_id)
        prefill_ctx = trace_ctx.child() if trace_ctx is not None else None
        try:
            status, prefill_body = await send_request_to_prefiller(
                session, prefill_url, endpoint, prefill_json, request_id,
                trace_ctx=prefill_ctx,
                timeout=_phase_timeout(),
            )
            if status >= 400:
                # 4xx: the CLIENT's fault and the prefiller is alive —
                # forward verbatim; retrying it against other prefillers
                # would trip every healthy breaker on bad client traffic
                monitor.on_request_complete(prefill_url, request_id)
                breakers.record_success(prefill_url)
                return web.json_response(prefill_body, status=status)
            monitor.on_request_response(prefill_url, request_id)
            monitor.on_request_complete(prefill_url, request_id)
            breakers.record_success(prefill_url)
            logger.info("Prefill of %s done in %.3fs (TTFT)", request_id, time.time() - t0)
            get_collector().record(
                "router.disagg_prefill", prefill_ctx, t0, time.time() - t0,
                backend=prefill_url, request_id=request_id, attempt=attempt,
            )
            break
        except (_RetryableProxyError, aiohttp.ClientError, asyncio.TimeoutError,
                ConnectionResetError) as e:
            monitor.on_request_complete(prefill_url, request_id)
            shed = isinstance(e, _RetryableProxyError) and e.is_shed
            if not shed:  # sheds are capacity, not failure: breaker-neutral
                breakers.record_failure(prefill_url)
            if isinstance(e, asyncio.TimeoutError):
                count_deadline_abort("ttft")
                spawn_abort(prefill_url, request_id)
            get_collector().record(
                "router.disagg_prefill", prefill_ctx, t0, time.time() - t0,
                backend=prefill_url, request_id=request_id, attempt=attempt,
                error=str(e), outcome="retryable_error",
            )
            logger.error(
                "prefill on %s failed for request %s (attempt %d/%d): %s",
                prefill_url, request_id, attempt, policy.max_attempts, e,
            )
            remaining = policy.remaining(t_attempts0)
            if remaining is not None and remaining <= 0:
                count_deadline_abort("request")
                return web.json_response(
                    {"error": f"request deadline exceeded after {attempt} "
                              f"prefill attempt(s): {e}"},
                    status=504,
                )
            # untried endpoints only, and ROLE-correct: when the deployment
            # has prefill-labeled pods, failover must stay within them —
            # _pick's label fallback would otherwise silently run prefill on
            # a decode pod, breaking the disaggregation invariant. With no
            # labeled pods anywhere (label-less test rigs) any pod is fair.
            sat = get_saturation_registry()
            pool = [ep for ep in endpoints
                    if ep.url not in tried and not sat.is_saturated(ep.url)]
            if any(ep.model_label in router.prefill_labels for ep in endpoints):
                pool = [ep for ep in pool
                        if ep.model_label in router.prefill_labels]
            if attempt >= policy.max_attempts or not pool:
                if shed:
                    return _overloaded_response(
                        f"all prefillers saturated after {attempt} attempt(s)",
                        e.retry_after,
                    )
                return web.json_response(
                    {"error": f"prefill failed after {attempt} attempt(s): {e}"},
                    status=502,
                )
            count_retry()
            count_failover()
            delay = 0.0 if shed else policy.backoff(attempt)
            if remaining is not None:
                delay = min(delay, max(0.0, remaining))
            await asyncio.sleep(delay)
            prefill_url = router.route_prefill(pool)

    async def pick_next_decode(excluded: set) -> Optional[str]:
        pool = [ep for ep in endpoints if ep.url not in excluded]
        pool = breakers.filter_endpoints(pool, fail_static=False)
        return router.route_decode(pool) if pool else None

    decode_json = dict(request_json)
    decode_json["max_tokens"] = orig_max_tokens
    decode_json.setdefault("kv_transfer_params", {})["request_id"] = request_id
    body = json.dumps(decode_json).encode()
    # ts_recv rides through so the router.request root span covers the WHOLE
    # P/D request (prefill phase included) — without it the root would start
    # at the decode proxy and the disagg_prefill child would fall outside
    # its parent's window, corrupting the attribution table
    return await process_request(
        request, body, decode_url, endpoint, request_id,
        is_streaming=bool(request_json.get("stream", False)),
        trace_ctx=trace_ctx, ts_recv=ts_recv, pick_next=pick_next_decode,
        attempts_anchor=t_attempts0,
    )


async def route_sleep_wakeup_request(
    request: web.Request, path: str
) -> web.Response:
    """Proxy /sleep, /wake_up, /is_sleeping to a specific engine chosen by
    ?url=... or model, and update discovery sleep flags
    (parity request.py:442-514)."""
    target = request.query.get("url")
    sd = get_service_discovery()
    candidates = [ep for ep in sd.get_endpoint_info() if target is None or ep.url == target]
    # sleeping endpoints are filtered from get_endpoint_info (k8s mode) but
    # must still be reachable for wake_up
    if hasattr(sd, "endpoints"):
        known = {c.url for c in candidates}
        for ep in getattr(sd, "endpoints").values():
            if ep.url not in known and (target is None or ep.url == target):
                candidates.append(ep)
    elif target is not None and not candidates and target in getattr(sd, "urls", []):
        candidates = [EndpointInfo(url=target, model_names=[], added_timestamp=0)]
    if not candidates:
        return web.json_response({"error": "no matching engine"}, status=404)
    ep = candidates[0]
    session = await get_client_session()
    try:
        if path == "/is_sleeping":
            async with session.get(f"{ep.url}{path}") as resp:
                return web.json_response(await resp.json(), status=resp.status)
        async with session.post(
            f"{ep.url}{path}", params={k: v for k, v in request.query.items() if k != "url"}
        ) as resp:
            status = resp.status
        if status == 200:
            await sd.set_sleep_label(ep.url, path == "/sleep")
        return web.Response(status=status)
    except aiohttp.ClientError as e:
        return web.json_response({"error": str(e)}, status=502)
