"""Engine-endpoint discovery: static list or Kubernetes pod watch.

Parity: src/vllm_router/service_discovery.py in /root/reference —
ServiceDiscovery ABC :175, StaticServiceDiscovery :203 (health loop :241-254),
K8sServiceDiscovery :326 (watch loop :542-574, _add_engine :576-620),
EndpointInfo :80-172, sleep-label handling :429-463.

TPU-native differences: asyncio tasks instead of daemon threads, and the K8s
watch speaks to the apiserver REST API directly over aiohttp (in-cluster
serviceaccount token) — the heavyweight `kubernetes` client is not needed.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import ssl
import time
from abc import ABC, abstractmethod
from typing import Optional

import aiohttp

from production_stack_tpu.router.utils import cancel_task, is_model_healthy
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

_global_service_discovery: Optional["ServiceDiscovery"] = None


@dataclasses.dataclass
class ModelInfo:
    id: str
    object: str = "model"
    created: int = 0
    owned_by: str = "production-stack-tpu"
    parent: Optional[str] = None
    is_adapter: bool = False

    @staticmethod
    def from_dict(d: dict) -> "ModelInfo":
        return ModelInfo(
            id=d.get("id", ""),
            created=d.get("created", 0),
            owned_by=d.get("owned_by", ""),
            parent=d.get("parent"),
            is_adapter=d.get("parent") is not None,
        )

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "object": self.object,
            "created": self.created,
            "owned_by": self.owned_by,
            "parent": self.parent,
        }


@dataclasses.dataclass
class EndpointInfo:
    url: str
    model_names: list[str]
    added_timestamp: float
    model_label: Optional[str] = None
    pod_name: Optional[str] = None
    namespace: Optional[str] = None
    sleep: bool = False
    model_info: dict = dataclasses.field(default_factory=dict)


class ServiceDiscovery(ABC):
    @abstractmethod
    def get_endpoint_info(self) -> list[EndpointInfo]: ...

    async def start(self) -> None:  # pragma: no cover - overridden
        pass

    async def close(self) -> None:
        pass

    def get_health(self) -> bool:
        return True

    async def set_sleep_label(self, url: str, sleep: bool) -> None:
        """Record an endpoint's sleep state (overridden per discovery kind)."""
        return None

    def get_model_names(self) -> list[str]:
        names: list[str] = []
        for ep in self.get_endpoint_info():
            for m in ep.model_names:
                if m not in names:
                    names.append(m)
        return names

    def get_unhealthy_endpoint_urls(self) -> list[str]:
        # passive circuit breaking feeds the health surface for every
        # discovery kind: a backend with an open breaker is unhealthy even
        # when no active health loop is configured
        return self._breaker_open_urls()

    @staticmethod
    def _breaker_open_urls() -> list[str]:
        from production_stack_tpu.router.resilience import get_breaker_registry

        return get_breaker_registry().open_urls()


class StaticServiceDiscovery(ServiceDiscovery):
    """Fixed URL list; optional periodic per-model health checks with real
    dummy payloads (parity: service_discovery.py:203-324)."""

    def __init__(
        self,
        urls: list[str],
        models: list[str],
        *,
        aliases: Optional[list[str]] = None,
        model_labels: Optional[list[str]] = None,
        model_types: Optional[list[str]] = None,
        static_backend_health_checks: bool = False,
        health_check_interval: float = 10.0,
        prefill_model_labels: Optional[list[str]] = None,
        decode_model_labels: Optional[list[str]] = None,
    ):
        self.urls = urls
        self.models = models
        self.aliases = aliases
        self.model_labels = model_labels or [None] * len(urls)
        self.model_types = model_types
        self.enable_health_checks = static_backend_health_checks
        self.health_check_interval = health_check_interval
        self.prefill_model_labels = prefill_model_labels or []
        self.decode_model_labels = decode_model_labels or []
        self.added = time.time()
        self.unhealthy: set[str] = set()
        self.sleeping: set[str] = set()
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        if self.enable_health_checks:
            self._task = asyncio.create_task(self._health_loop())

    async def close(self) -> None:
        if self._task:
            await cancel_task(self._task)
            self._task = None

    async def _health_loop(self) -> None:
        from production_stack_tpu.router.resilience import get_breaker_registry

        while True:
            try:
                unhealthy: set[str] = set()
                for url, model, mtype in zip(
                    self.urls, self.models, self.model_types or ["chat"] * len(self.urls)
                ):
                    if not await is_model_healthy(url, model, mtype):
                        unhealthy.add(url)
                    else:
                        # active-probe success fast-tracks an OPEN breaker to
                        # half-open (skipping the cooldown) but does NOT close
                        # it or reset the failure streak: a backend can pass
                        # the 1-token dummy probe while failing real traffic,
                        # so only a data-plane success may close the breaker
                        get_breaker_registry().record_probe_success(url)
                if unhealthy != self.unhealthy:
                    logger.warning("unhealthy endpoints: %s", sorted(unhealthy))
                self.unhealthy = unhealthy
            except Exception:
                logger.exception("health check loop error")
            await asyncio.sleep(self.health_check_interval)

    def get_unhealthy_endpoint_urls(self) -> list[str]:
        return sorted(set(self.unhealthy) | set(self._breaker_open_urls()))

    async def set_sleep_label(self, url: str, sleep: bool) -> None:
        if sleep:
            self.sleeping.add(url)
        else:
            self.sleeping.discard(url)

    def get_endpoint_info(self) -> list[EndpointInfo]:
        out = []
        for i, (url, model) in enumerate(zip(self.urls, self.models)):
            if url in self.unhealthy:
                continue
            label = self.model_labels[i] if i < len(self.model_labels) else None
            out.append(
                EndpointInfo(
                    url=url,
                    model_names=[model],
                    added_timestamp=self.added,
                    model_label=label,
                    sleep=url in self.sleeping,
                )
            )
        return out


class K8sPodIPServiceDiscovery(ServiceDiscovery):
    """Watch pods matching a label selector via the K8s REST API; query each
    ready pod's /v1/models to learn what it serves; track sleep state.

    Parity: service_discovery.py:326-718 (watch loop, _add_engine,
    _check_pod_ready, sleep labels). Talks to the apiserver directly:
    GET /api/v1/namespaces/{ns}/pods?labelSelector=...&watch=true with the
    serviceaccount bearer token.
    """

    TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"
    CA_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"

    def __init__(
        self,
        namespace: str = "default",
        label_selector: str = "",
        port: str = "8000",
        *,
        api_server: Optional[str] = None,
        token: Optional[str] = None,
        prefill_model_labels: Optional[list[str]] = None,
        decode_model_labels: Optional[list[str]] = None,
    ):
        self.namespace = namespace
        self.label_selector = label_selector
        self.port = port
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        kport = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        scheme = "https" if kport in ("443", "6443") else "http"
        self.api_server = api_server or f"{scheme}://{host}:{kport}"
        self._token = token
        self.prefill_model_labels = prefill_model_labels or []
        self.decode_model_labels = decode_model_labels or []
        self.endpoints: dict[str, EndpointInfo] = {}
        self._lock = asyncio.Lock()
        self._task: Optional[asyncio.Task] = None
        self._healthy = False

    def _auth_headers(self) -> dict:
        token = self._token
        if token is None and os.path.exists(self.TOKEN_PATH):
            with open(self.TOKEN_PATH) as f:
                token = f.read().strip()
        return {"Authorization": f"Bearer {token}"} if token else {}

    def _ssl_ctx(self):
        if not self.api_server.startswith("https"):
            return None
        if os.path.exists(self.CA_PATH):
            return ssl.create_default_context(cafile=self.CA_PATH)
        # https apiserver without the in-cluster CA (out-of-cluster dev against
        # a self-signed apiserver): skip verification rather than fail forever
        return False

    async def start(self) -> None:
        self._task = asyncio.create_task(self._watch_loop())

    async def close(self) -> None:
        if self._task:
            await cancel_task(self._task)
            self._task = None

    def get_health(self) -> bool:
        return self._healthy

    def get_endpoint_info(self) -> list[EndpointInfo]:
        return [ep for ep in self.endpoints.values() if not ep.sleep]

    async def _watch_loop(self) -> None:
        url = f"{self.api_server}/api/v1/namespaces/{self.namespace}/pods"
        params = {"watch": "true", "timeoutSeconds": "30"}
        if self.label_selector:
            params["labelSelector"] = self.label_selector
        while True:
            try:
                # token read off the loop: _auth_headers re-reads the mounted
                # serviceaccount token file on every watch (re)connect (kubelet
                # rotates it), and a slow/overloaded kubelet volume must not
                # stall in-flight streaming proxies (graftcheck GC001)
                headers = await asyncio.to_thread(self._auth_headers)
                async with aiohttp.ClientSession(
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(total=None, sock_read=60),
                ) as session:
                    async with session.get(url, params=params, ssl=self._ssl_ctx()) as resp:
                        resp.raise_for_status()
                        self._healthy = True
                        async for line in resp.content:
                            if line.strip():
                                await self._on_event(json.loads(line))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self._healthy = False
                logger.warning("k8s watch error (%s); retrying", e)
                await asyncio.sleep(0.5)

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        statuses = (pod.get("status", {}).get("containerStatuses")) or []
        return bool(statuses) and all(s.get("ready") for s in statuses)

    async def _get_model_names(self, pod_ip: str) -> list[dict]:
        url = f"http://{pod_ip}:{self.port}/v1/models"
        try:
            async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5)
            ) as session:
                async with session.get(url) as resp:
                    data = await resp.json()
                    return data.get("data", [])
        except Exception:
            return []

    async def _on_event(self, event: dict) -> None:
        etype = event.get("type")
        pod = event.get("object", {})
        name = pod.get("metadata", {}).get("name", "")
        labels = pod.get("metadata", {}).get("labels", {}) or {}
        pod_ip = pod.get("status", {}).get("podIP")
        if etype == "DELETED" or not self._pod_ready(pod) or not pod_ip:
            async with self._lock:
                if name in self.endpoints:
                    logger.info("Removing engine %s", name)
                    # drop the pod's breaker with it: a replacement pod that
                    # reuses the IP must start closed, not inherit the
                    # corpse's open state
                    from production_stack_tpu.router.resilience import (
                        get_breaker_registry,
                    )

                    get_breaker_registry().forget(self.endpoints[name].url)
                    del self.endpoints[name]
            return
        models = await self._get_model_names(pod_ip)
        if not models:
            return
        url = f"http://{pod_ip}:{self.port}"
        sleep = labels.get("sleep") == "true"
        async with self._lock:
            self.endpoints[name] = EndpointInfo(
                url=url,
                model_names=[m["id"] for m in models],
                added_timestamp=time.time(),
                model_label=labels.get("model"),
                pod_name=name,
                namespace=self.namespace,
                sleep=sleep,
                model_info={m["id"]: m for m in models},
            )
            logger.info("Discovered engine %s at %s serving %s", name, url,
                        [m["id"] for m in models])

    async def set_sleep_label(self, url: str, sleep: bool) -> None:
        """Mark an endpoint sleeping/awake (mirrors pod relabeling,
        service_discovery.py:429-463)."""
        async with self._lock:
            for ep in self.endpoints.values():
                if ep.url == url:
                    ep.sleep = sleep


def initialize_service_discovery(kind: str, **kwargs) -> ServiceDiscovery:
    global _global_service_discovery
    if kind == "static":
        sd = StaticServiceDiscovery(**kwargs)
    elif kind == "k8s":
        sd = K8sPodIPServiceDiscovery(**kwargs)
    else:
        raise ValueError(f"unknown service discovery type: {kind}")
    _global_service_discovery = sd
    return sd


def get_service_discovery() -> ServiceDiscovery:
    assert _global_service_discovery is not None, "service discovery not initialized"
    return _global_service_discovery


def set_service_discovery(sd: ServiceDiscovery) -> None:
    global _global_service_discovery
    _global_service_discovery = sd
