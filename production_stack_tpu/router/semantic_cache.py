"""Semantic response cache (experimental, behind --feature-gates SemanticCache=true).

Parity: src/vllm_router/experimental/semantic_cache/ in /root/reference
(SemanticCache semantic_cache.py:16-120+, FAISSAdapter db_adapters/
faiss_adapter.py:14-134, integration check/store hooks).

The reference embeds with sentence-transformers and searches a FAISS index;
neither ships in this environment, so the default embedder is a hashed
character-n-gram featurizer (deterministic, dependency-free) with exact
brute-force cosine search over a numpy matrix — the right structure with a
pluggable `embed` function where a real encoder can drop in.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Callable, Optional

import numpy as np

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

DIM = 256


def ngram_hash_embed(text: str, dim: int = DIM) -> np.ndarray:
    """Hashed char-3gram bag embedding, L2-normalized."""
    v = np.zeros(dim, np.float32)
    t = text.lower()
    for i in range(max(len(t) - 2, 1)):
        g = t[i : i + 3]
        h = int.from_bytes(hashlib.blake2b(g.encode(), digest_size=4).digest(), "little")
        v[h % dim] += 1.0
    n = np.linalg.norm(v)
    return v / n if n > 0 else v


class SemanticCache:
    def __init__(
        self,
        threshold: float = 0.92,
        max_entries: int = 4096,
        embed: Optional[Callable[[str], np.ndarray]] = None,
    ):
        self.threshold = threshold
        self.max_entries = max_entries
        self.embed = embed or ngram_hash_embed
        self.vectors = np.zeros((0, DIM), np.float32)
        self.entries: list[dict] = []
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _prompt_of(body: bytes) -> Optional[str]:
        try:
            data = json.loads(body)
        except json.JSONDecodeError:
            return None
        msgs = data.get("messages")
        if not msgs or data.get("stream"):
            return None  # only cache non-streaming chat requests
        return json.dumps(msgs, sort_keys=True)

    async def check(self, body: bytes) -> Optional[dict]:
        prompt = self._prompt_of(body)
        if prompt is None or len(self.entries) == 0:
            self.misses += 1
            return None
        q = self.embed(prompt)
        sims = self.vectors @ q
        best = int(np.argmax(sims))
        if sims[best] >= self.threshold:
            self.hits += 1
            logger.info("semantic cache hit (sim=%.3f)", float(sims[best]))
            return self.entries[best]["response"]
        self.misses += 1
        return None

    async def store(self, body: bytes, response: dict) -> None:
        prompt = self._prompt_of(body)
        if prompt is None:
            return
        q = self.embed(prompt)
        self.vectors = np.vstack([self.vectors, q[None]])
        self.entries.append({"response": response, "ts": time.time()})
        if len(self.entries) > self.max_entries:
            self.vectors = self.vectors[1:]
            self.entries.pop(0)
