"""Semantic response cache (experimental, behind --feature-gates SemanticCache=true).

Parity: src/vllm_router/experimental/semantic_cache/ in /root/reference
(SemanticCache semantic_cache.py:16-120+, FAISSAdapter db_adapters/
faiss_adapter.py:14-134, integration check/store hooks).

Backends are optional-import, mirroring the reference's dependency split
(pyproject extra ``semantic_cache``): when ``sentence-transformers`` is
installed the embedder is a real sentence encoder, and when ``faiss`` is
installed similarity search runs on an ``IndexFlatIP``. Neither ships in
hermetic environments, so the always-available fallbacks are a hashed
character-n-gram featurizer (deterministic, dependency-free) and exact
brute-force cosine search over a numpy matrix — same interfaces, proven by
the unit tests with fake modules.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Callable, Optional

import numpy as np

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

DIM = 256


def ngram_hash_embed(text: str, dim: int = DIM) -> np.ndarray:
    """Hashed char-3gram bag embedding, L2-normalized."""
    v = np.zeros(dim, np.float32)
    t = text.lower()
    for i in range(max(len(t) - 2, 1)):
        g = t[i : i + 3]
        h = int.from_bytes(hashlib.blake2b(g.encode(), digest_size=4).digest(), "little")
        v[h % dim] += 1.0
    n = np.linalg.norm(v)
    return v / n if n > 0 else v


class SentenceTransformerEmbedder:
    """Real sentence encoder (reference: semantic_cache.py uses
    sentence-transformers). Activates when the package is installed; inject
    ``module`` to test the adapter without it."""

    def __init__(self, model_name: str = "all-MiniLM-L6-v2", module=None):
        if module is None:
            import sentence_transformers as module  # optional dep
        self._model = module.SentenceTransformer(model_name)
        self.dim = int(self._model.get_sentence_embedding_dimension())

    def __call__(self, text: str) -> np.ndarray:
        v = np.asarray(self._model.encode([text])[0], np.float32)
        n = np.linalg.norm(v)
        return v / n if n > 0 else v


class NumpyIndex:
    """Exact brute-force cosine search (vectors pre-normalized)."""

    def __init__(self, dim: int):
        self.vectors = np.zeros((0, dim), np.float32)

    def __len__(self) -> int:
        return len(self.vectors)

    def add(self, v: np.ndarray) -> None:
        self.vectors = np.vstack([self.vectors, v[None]])

    def search(self, q: np.ndarray) -> "tuple[float, int]":
        if not len(self.vectors):
            return -1.0, -1
        sims = self.vectors @ q
        best = int(np.argmax(sims))
        return float(sims[best]), best

    def pop_front(self, k: int = 1) -> None:
        self.vectors = self.vectors[k:]


class FaissIndex:
    """FAISS ``IndexFlatIP`` adapter (reference: faiss_adapter.py:14-134 —
    inner product over normalized vectors == cosine). Inject ``module`` to
    test without faiss installed."""

    def __init__(self, dim: int, module=None):
        if module is None:
            import faiss as module  # optional dep
        self._faiss = module
        self.dim = dim
        self._index = module.IndexFlatIP(dim)
        self._count = 0

    def __len__(self) -> int:
        return self._count

    def add(self, v: np.ndarray) -> None:
        self._index.add(np.ascontiguousarray(v[None], np.float32))
        self._count += 1

    def search(self, q: np.ndarray) -> "tuple[float, int]":
        if not self._count:
            return -1.0, -1
        sims, ids = self._index.search(np.ascontiguousarray(q[None], np.float32), 1)
        return float(sims[0, 0]), int(ids[0, 0])

    def pop_front(self, k: int = 1) -> None:
        # IndexFlatIP stores vectors densely: eviction is an O(n*dim) rebuild
        # without the dropped rows, so callers evict in batches to keep the
        # steady-state store path O(1) amortized.
        n = self._count
        k = min(k, n)
        kept = np.vstack(
            [self._index.reconstruct(i) for i in range(k, n)]
        ) if n > k else np.zeros((0, self.dim), np.float32)
        self._index = self._faiss.IndexFlatIP(self.dim)
        if len(kept):
            self._index.add(np.ascontiguousarray(kept, np.float32))
        self._count = n - k


def default_embedder() -> "tuple[Callable[[str], np.ndarray], int]":
    """(embed_fn, dim): sentence-transformers when installed AND its model is
    already cached locally, else n-grams. The probe runs HF-offline so a
    router in an air-gapped cluster fails fast to the fallback instead of
    stalling startup on download retries; pre-download the model (or bake it
    into the image) to activate the real embedder."""
    import os

    prev = os.environ.get("HF_HUB_OFFLINE")
    os.environ["HF_HUB_OFFLINE"] = "1"
    try:
        emb = SentenceTransformerEmbedder()
        logger.info("semantic cache: sentence-transformers embedder (dim=%d)", emb.dim)
        return emb, emb.dim
    except Exception:  # noqa: BLE001 - package absent or model not cached
        return ngram_hash_embed, DIM
    finally:
        if prev is None:
            os.environ.pop("HF_HUB_OFFLINE", None)
        else:
            os.environ["HF_HUB_OFFLINE"] = prev


def default_index(dim: int):
    """FAISS IndexFlatIP when installed, else exact numpy search."""
    try:
        idx = FaissIndex(dim)
        logger.info("semantic cache: FAISS IndexFlatIP backend")
        return idx
    except Exception:  # noqa: BLE001
        return NumpyIndex(dim)


class SemanticCache:
    def __init__(
        self,
        threshold: float = 0.92,
        max_entries: int = 4096,
        embed: Optional[Callable[[str], np.ndarray]] = None,
        index=None,
    ):
        self.threshold = threshold
        self.max_entries = max_entries
        if embed is None:
            embed, dim = default_embedder()
        else:
            dim = getattr(embed, "dim", DIM)
        self.embed = embed
        self.index = index if index is not None else default_index(dim)
        self.entries: list[dict] = []
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _prompt_of(body: bytes) -> Optional[str]:
        try:
            data = json.loads(body)
        except json.JSONDecodeError:
            return None
        msgs = data.get("messages")
        if not msgs or data.get("stream"):
            return None  # only cache non-streaming chat requests
        return json.dumps(msgs, sort_keys=True)

    async def check(self, body: bytes) -> Optional[dict]:
        prompt = self._prompt_of(body)
        if prompt is None or len(self.entries) == 0:
            self.misses += 1
            return None
        sim, best = self.index.search(self.embed(prompt))
        if best >= 0 and sim >= self.threshold:
            self.hits += 1
            logger.info("semantic cache hit (sim=%.3f)", sim)
            return self.entries[best]["response"]
        self.misses += 1
        return None

    async def store(self, body: bytes, response: dict) -> None:
        prompt = self._prompt_of(body)
        if prompt is None:
            return
        self.index.add(self.embed(prompt))
        self.entries.append({"response": response, "ts": time.time()})
        if len(self.entries) > self.max_entries:
            # Batch-evict the oldest eighth: a FAISS flat index can only
            # evict via full rebuild, so amortize that cost over many stores
            # instead of paying O(n*dim) on every miss once the cache fills.
            k = max(1, self.max_entries // 8)
            self.index.pop_front(k)
            del self.entries[:k]
