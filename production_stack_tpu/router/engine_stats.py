"""Engine statistics scraper.

Parity: src/vllm_router/stats/engine_stats.py in /root/reference —
EngineStats.from_vllm_scrape :42-85, EngineStatsScraper (interval worker)
:88-218. Scrapes each engine's Prometheus /metrics text and extracts the
`vllm:*` gauges our TPU engine also emits (engine/api_server.py), so the same
scraper works against vLLM pods and TPU pods.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Optional

import aiohttp

from production_stack_tpu.router.utils import SingletonMeta
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


@dataclasses.dataclass
class EngineStats:
    num_running_requests: int = 0
    num_queuing_requests: int = 0
    gpu_prefix_cache_hit_rate: float = 0.0
    gpu_prefix_cache_hits_total: float = 0.0
    gpu_prefix_cache_queries_total: float = 0.0
    gpu_cache_usage_perc: float = 0.0
    # engine-side admission control state (api_server overload surface):
    # routing deprioritizes saturated backends between Retry-After windows
    engine_saturated: int = 0
    # offload restore economics, scraped for KV-aware routing v2: the
    # engine's linkprobe-derived per-operation restore cap (engine-measured
    # restore-vs-recompute crossover; -1 = not exported, <=0 = unbounded)
    # and the measured host<->device link bandwidth (0 = not exported)
    kv_offload_max_io_pages: float = -1.0
    kv_offload_link_bandwidth_bytes_per_sec: float = 0.0
    # serving-mesh tp degree (chips per replica): capacity math — a tp=4
    # engine is ONE replica on 4 chips, not 4x the seats; the fleet
    # controller and dashboards read it through the router's scrape
    tensor_parallel: float = 1.0
    # KV pool bytes per token (ops/quant.py): an int8-KV engine streams
    # half the bytes AND holds ~2x the tokens per GB — capacity-aware
    # consumers (dashboards, the fleet controller) read the real number
    # instead of assuming the fp16 footprint (0 = not exported)
    kv_cache_dtype_bytes_per_token: float = 0.0
    # KV fabric transfer economics (kvfabric/peers.py, docs/kv-fabric.md):
    # probed engine-to-engine bandwidth summed over that engine's peer links
    # (from_scrape sums label sets) and the fabric listener's in-flight op
    # count — the disagg router and fleet controller combine them into a
    # transfer-cost score bw/(1+depth) per NetKV (0 = fabric not enabled)
    kv_fabric_peer_bandwidth_bytes_per_sec: float = 0.0
    kv_fabric_queue_depth: float = 0.0

    _FIELDS = {
        "vllm:num_requests_running": "num_running_requests",
        "vllm:num_requests_waiting": "num_queuing_requests",
        "vllm:gpu_prefix_cache_hit_rate": "gpu_prefix_cache_hit_rate",
        "vllm:gpu_prefix_cache_hits_total": "gpu_prefix_cache_hits_total",
        "vllm:gpu_prefix_cache_queries_total": "gpu_prefix_cache_queries_total",
        "vllm:gpu_cache_usage_perc": "gpu_cache_usage_perc",
        "vllm:engine_saturated": "engine_saturated",
        "vllm:kv_offload_max_io_pages": "kv_offload_max_io_pages",
        "vllm:kv_offload_link_bandwidth_bytes_per_sec": (
            "kv_offload_link_bandwidth_bytes_per_sec"
        ),
        "vllm:tensor_parallel_degree": "tensor_parallel",
        "vllm:kv_cache_dtype_bytes_per_token": "kv_cache_dtype_bytes_per_token",
        "vllm:kv_fabric_peer_bandwidth_bytes_per_sec": (
            "kv_fabric_peer_bandwidth_bytes_per_sec"
        ),
        "vllm:kv_fabric_queue_depth": "kv_fabric_queue_depth",
    }

    @staticmethod
    def from_scrape(text: str) -> "EngineStats":
        """Parse Prometheus exposition text, summing across label sets."""
        vals: dict[str, float] = {}
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                name_part, value = line.rsplit(None, 1)
                name = name_part.split("{")[0]
                if name in EngineStats._FIELDS:
                    vals[name] = vals.get(name, 0.0) + float(value)
            except ValueError:
                continue
        stats = EngineStats()
        for metric, attr in EngineStats._FIELDS.items():
            if metric in vals:
                setattr(stats, attr, type(getattr(stats, attr))(vals[metric]))
        # derive hit rate from counters when the gauge is absent (vLLM v1)
        if stats.gpu_prefix_cache_queries_total > 0 and stats.gpu_prefix_cache_hit_rate == 0:
            stats.gpu_prefix_cache_hit_rate = (
                stats.gpu_prefix_cache_hits_total / stats.gpu_prefix_cache_queries_total
            )
        return stats


class EngineStatsScraper(metaclass=SingletonMeta):
    # a snapshot older than this many scrape intervals is stale: load-aware
    # routing must stop trusting a dead pod's last-good queue depth
    STALE_INTERVALS = 3.0

    def __init__(self, scrape_interval: float = 15.0):
        self.scrape_interval = scrape_interval
        self.engine_stats: dict[str, EngineStats] = {}
        self.last_success: dict[str, float] = {}  # url -> monotonic ts
        # restart epochs: bumped when a backend's counters regress (the
        # process restarted) or when a dropped-for-staleness backend scrapes
        # again — a reborn pod's first successful scrape starts a NEW epoch,
        # so routing never blends pre-restart state into it (no lingering
        # saturation window, no stale-snapshot quarantine on the newborn)
        self.epochs: dict[str, int] = {}
        self._dropped_stale: set[str] = set()
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def close(self) -> None:
        from production_stack_tpu.router.utils import cancel_task

        if self._task:
            await cancel_task(self._task)
            self._task = None

    async def _loop(self) -> None:
        import time

        from production_stack_tpu.router.service_discovery import get_service_discovery

        while True:
            try:
                endpoints = get_service_discovery().get_endpoint_info()
                results = await asyncio.gather(
                    *[self._scrape_one(ep.url) for ep in endpoints]
                )
                self.apply_scrape_results(
                    [ep.url for ep in endpoints], results, time.monotonic()
                )
            except Exception:
                logger.exception("engine stats scrape failed")
            try:
                # per-request SLO terminal records (router/slo.py): the same
                # scrape cadence pulls each backend's /slo_records tail and
                # feeds the attainment counters. Separate try: a broken SLO
                # surface on one pod must not cost the fleet its load stats.
                endpoints = get_service_discovery().get_endpoint_info()
                await asyncio.gather(
                    *[self._scrape_slo_records(ep.url) for ep in endpoints]
                )
            except Exception:
                logger.exception("slo records scrape failed")
            await asyncio.sleep(self.scrape_interval)

    async def _scrape_slo_records(self, url: str) -> None:
        """Pull one backend's new SLO terminal records (cursor-based) into
        the SLO monitor. Best-effort per backend: plain-vLLM pods without
        /slo_records (404) and dead pods are silently skipped."""
        from production_stack_tpu.router.request_service import get_client_session
        from production_stack_tpu.router.slo import get_slo_monitor

        slo = get_slo_monitor()
        try:
            session = await get_client_session()
            async with session.get(
                f"{url}/slo_records",
                params={"since": str(slo.cursor(url))},
                timeout=aiohttp.ClientTimeout(total=5),
            ) as resp:
                if resp.status != 200:
                    return
                payload = await resp.json()
        except Exception:  # noqa: BLE001 - scrape is best-effort
            return
        slo.ingest(url, payload)

    def apply_scrape_results(
        self, urls: list[str], results: list[Optional[EngineStats]], now: float
    ) -> None:
        """Merge one scrape round. A failed scrape (None) keeps the previous
        snapshot only within the staleness window — after STALE_INTERVALS
        scrape intervals without a success the entry is DROPPED, so
        load-aware routing stops trusting a dead pod's old queue depth."""
        fresh = {url: st for url, st in zip(urls, results) if st is not None}
        for url, st in fresh.items():
            prev = self.engine_stats.get(url)
            # restart detection: Prometheus counters only move forward within
            # one process lifetime, so a regression means the engine was
            # reborn. Also: a backend that was dropped for staleness and now
            # scrapes again came back from the dead (restart or partition).
            reborn = (
                prev is not None
                and st.gpu_prefix_cache_queries_total
                < prev.gpu_prefix_cache_queries_total
            ) or (url in self._dropped_stale)
            if reborn:
                self.epochs[url] = self.epochs.get(url, 0) + 1
                self._dropped_stale.discard(url)
                logger.info(
                    "engine %s restarted (stats epoch %d): clearing its "
                    "pre-restart saturation window", url, self.epochs[url],
                )
                # a Retry-After window from the previous incarnation must
                # not keep routing away from an engine with an empty queue;
                # the breaker is deliberately NOT reset — the reborn backend
                # re-enters traffic through the normal half-open probe
                from production_stack_tpu.router.resilience import (
                    get_saturation_registry,
                )

                get_saturation_registry().forget(url)
        self.engine_stats.update(fresh)
        for url in fresh:
            self.last_success[url] = now
        for url in list(self.engine_stats):
            if url not in urls:
                del self.engine_stats[url]
                self.last_success.pop(url, None)
        # sweep per-backend bookkeeping for urls gone from the CONFIG —
        # including ones already stale-dropped from engine_stats (an
        # autoscaled fleet churning per-pod urls would otherwise leak these
        # forever, and a reused address would inherit a bogus 'reborn' epoch)
        current = set(urls)
        for url in list(self._dropped_stale):
            if url not in current:
                self._dropped_stale.discard(url)
        for url in list(self.epochs):
            if url not in current:
                del self.epochs[url]
                # migration session pins must not keep steering sessions at
                # a backend removed from the config (resilience.py)
                from production_stack_tpu.router.resilience import (
                    get_session_pins,
                )

                get_session_pins().forget_backend(url)
                # deliberately NOT resetting the SLO cursor here: a backend
                # can drop out of discovery without restarting (health-check
                # flap under overload — exactly when SLO data matters), and
                # a reset would re-ingest its retained records on rejoin,
                # double-counting attainment. A genuinely reborn process
                # starts a fresh record counter, which ingest() detects via
                # head < cursor and resets on its own.
        cutoff = now - self.STALE_INTERVALS * self.scrape_interval
        for url in list(self.engine_stats):
            if self.last_success.get(url, now) < cutoff:
                logger.warning(
                    "dropping stale engine stats for %s (no successful "
                    "scrape in %.0fs)", url, now - self.last_success[url],
                )
                del self.engine_stats[url]
                self._dropped_stale.add(url)

    async def _scrape_one(self, url: str) -> Optional[EngineStats]:
        from production_stack_tpu.router.request_service import get_client_session

        try:
            session = await get_client_session()
            async with session.get(
                f"{url}/metrics", timeout=aiohttp.ClientTimeout(total=5)
            ) as resp:
                return EngineStats.from_scrape(await resp.text())
        except Exception:
            return None

    def get_engine_stats(self) -> dict[str, EngineStats]:
        return dict(self.engine_stats)

    def get_health(self) -> bool:
        return self._task is not None and not self._task.done()


def initialize_engine_stats_scraper(scrape_interval: float = 15.0) -> EngineStatsScraper:
    return EngineStatsScraper(scrape_interval)


def get_engine_stats_scraper() -> EngineStatsScraper:
    return EngineStatsScraper()
