"""Pluggable request rewriting before the request is sent to an engine.

Parity: src/vllm_router/services/request_service/rewriter.py:30-119 in
/root/reference (abstract RequestRewriter; `noop` is the only built-in).
"""

from __future__ import annotations

import abc
from typing import Optional


class RequestRewriter(abc.ABC):
    @abc.abstractmethod
    def rewrite_request(self, body: bytes, model: str, endpoint: str) -> bytes: ...


class NoopRequestRewriter(RequestRewriter):
    def rewrite_request(self, body: bytes, model: str, endpoint: str) -> bytes:
        return body


_rewriter: RequestRewriter = NoopRequestRewriter()


def initialize_rewriter(kind: Optional[str]) -> RequestRewriter:
    global _rewriter
    if kind in (None, "", "noop"):
        _rewriter = NoopRequestRewriter()
    else:
        raise ValueError(f"unknown rewriter: {kind}")
    return _rewriter


def get_rewriter() -> RequestRewriter:
    return _rewriter
