"""Router CLI parser.

Parity: src/vllm_router/parsers/parser.py in /root/reference (flag surface
:96-320, JSON config seeding :44-52, validation :69-93).
"""

from __future__ import annotations

import argparse
import json
import sys

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


def load_initial_config_from_config_json_if_required(argv: list[str]) -> list[str]:
    """`--config <file.json>` seeds defaults; explicit CLI flags win."""
    if "--config" not in argv:
        return argv
    idx = argv.index("--config")
    path = argv[idx + 1]
    with open(path) as f:
        cfg = json.load(f)
    seeded = []
    for k, v in cfg.items():
        flag = "--" + k.replace("_", "-")
        if flag in argv:
            continue
        if isinstance(v, bool):
            if v:
                seeded.append(flag)
        else:
            seeded.extend([flag, str(v)])
    return argv[:idx] + argv[idx + 2 :] + seeded


def parse_args(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    argv = load_initial_config_from_config_json_if_required(argv)
    p = argparse.ArgumentParser("tpu-router")
    p.add_argument("--config", type=str, default=None, help="JSON config seeding defaults")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--service-discovery", choices=["static", "k8s"], default="static")
    p.add_argument("--static-backends", type=str, default=None,
                   help="comma-separated engine URLs")
    p.add_argument("--static-models", type=str, default=None,
                   help="comma-separated model names (one per backend)")
    p.add_argument("--static-aliases", type=str, default=None)
    p.add_argument("--static-model-labels", type=str, default=None)
    p.add_argument("--static-model-types", type=str, default=None)
    p.add_argument("--static-backend-health-checks", action="store_true")
    p.add_argument("--health-check-interval", type=float, default=10.0)
    p.add_argument("--k8s-namespace", default="default")
    p.add_argument("--k8s-label-selector", default="")
    p.add_argument("--k8s-port", default="8000")
    p.add_argument("--routing-logic", default="roundrobin",
                   choices=["roundrobin", "session", "kvaware", "prefixaware",
                            "disaggregated_prefill"])
    p.add_argument("--session-key", type=str, default=None)
    p.add_argument("--kv-controller-url", type=str, default=None)
    p.add_argument("--kv-directory-url", type=str, default=None,
                   help="fleet-wide KV directory address (the cache server, "
                        "docs/kv-directory.md): kvaware routing v2 ranks "
                        "backends resident > restorable > cold against it")
    p.add_argument("--tokenizer", type=str, default=None)
    p.add_argument("--prefill-model-labels", type=str, default=None)
    p.add_argument("--decode-model-labels", type=str, default=None)
    p.add_argument("--model-aliases", type=str, default=None, help="JSON dict")
    p.add_argument("--engine-stats-interval", type=float, default=15.0)
    p.add_argument("--request-stats-window", type=float, default=60.0)
    p.add_argument("--log-stats", action="store_true")
    p.add_argument("--log-stats-interval", type=float, default=10.0)
    # unauthenticated state-mutating debug endpoints (POST /metrics/reset);
    # benchmark/test harnesses only
    p.add_argument("--enable-debug-endpoints", action="store_true")
    p.add_argument("--dynamic-config-json", type=str, default=None)
    p.add_argument("--enable-batch-api", action="store_true")
    p.add_argument("--file-storage-path", type=str, default="/tmp/tpu_router_files")
    p.add_argument("--batch-db-path", type=str, default="/tmp/tpu_router_batches.sqlite")
    p.add_argument("--callbacks", type=str, default=None,
                   help="path.py:instance of CustomCallbackHandler")
    p.add_argument("--feature-gates", type=str, default="",
                   help="e.g. SemanticCache=true,PIIDetection=true")
    p.add_argument("--semantic-cache-threshold", type=float, default=0.92)
    p.add_argument("--semantic-cache-embedder", type=str, default="auto",
                   choices=["auto", "ngram", "sentence-transformers"],
                   help="auto probes for a locally-cached sentence-transformers "
                        "model (HF-offline, fails fast) and falls back to the "
                        "dependency-free n-gram embedder")
    p.add_argument("--pii-policy", type=str, default="redact",
                   choices=["redact", "block"])
    p.add_argument("--pii-analyzer", type=str, default="auto",
                   choices=["auto", "regex", "presidio"],
                   help="presidio activates the NER tier (requires "
                        "presidio-analyzer); auto falls back to regex")
    p.add_argument("--sentry-dsn", type=str, default=None)
    p.add_argument("--trace-sample-rate", type=float, default=1.0,
                   help="fraction of requests recorded by the distributed "
                        "tracer (head-based, decided at the router and "
                        "propagated via traceparent); 0.0 disables span "
                        "recording entirely")
    p.add_argument("--trace-buffer-size", type=int, default=4096,
                   help="span ring-buffer capacity (bounds tracer memory)")
    # failure-domain layer (docs/failure-handling.md): retry/failover,
    # deadlines, passive circuit breaking
    p.add_argument("--retry-max-attempts", type=int, default=3,
                   help="proxy attempt budget per request (connect-stage and "
                        "pre-first-byte failures fail over to the routing "
                        "logic's next choice; 1 = no retries)")
    p.add_argument("--retry-backoff-base", type=float, default=0.05,
                   help="base backoff seconds between proxy attempts "
                        "(exponential with full jitter)")
    p.add_argument("--retry-backoff-max", type=float, default=2.0,
                   help="backoff cap in seconds")
    p.add_argument("--deadline-request", type=float, default=0.0,
                   help="seconds the ATTEMPT phase (connect + retries up to "
                        "first byte) may take before the request 504s; 0 "
                        "disables. Does not bound an already-streaming "
                        "response")
    p.add_argument("--deadline-ttft", type=float, default=0.0,
                   help="seconds to wait for the backend's first response "
                        "byte before aborting the engine-side request and "
                        "failing over; 0 disables. NOTE: a non-streaming "
                        "response's first byte arrives only when generation "
                        "COMPLETES — set this above worst-case non-stream "
                        "generation time (or serve long requests streamed)")
    p.add_argument("--deadline-inter-chunk", type=float, default=0.0,
                   help="max seconds between streamed chunks before the "
                        "stream is aborted on the engine and terminated "
                        "with an SSE error event; 0 disables")
    p.add_argument("--breaker-failure-threshold", type=int, default=5,
                   help="consecutive proxy failures that open a backend's "
                        "circuit breaker (0 disables circuit breaking)")
    p.add_argument("--breaker-cooldown", type=float, default=30.0,
                   help="seconds an open breaker waits before admitting a "
                        "half-open probe request")
    # per-request SLO accounting (router/slo.py, docs/observability.md):
    # objectives applied to the terminal records scraped from each engine's
    # /slo_records, exported as vllm_router:slo_{attained,violated}_total
    p.add_argument("--slo-ttft-ms", type=float, default=2000.0,
                   help="TTFT objective in ms for the per-backend SLO "
                        "attainment counters (objective=\"ttft\")")
    p.add_argument("--slo-itl-ms", type=float, default=200.0,
                   help="inter-token-latency p99 objective in ms for the "
                        "SLO attainment counters (objective=\"itl\")")
    p.add_argument("--saturation-queue-ref", type=int, default=8,
                   help="waiting-queue depth that counts one backend as "
                        "fully saturated in vllm_router:fleet_saturation "
                        "(the prometheus-adapter autoscaling gauge)")
    p.add_argument("--batch-avoid-attainment", type=float, default=0.9,
                   help="interactive-TTFT attainment ratio below which a "
                        "backend stops receiving NEW batch-class traffic "
                        "(X-Priority: batch); 0 disables class-aware "
                        "placement (docs/failure-handling.md)")
    args = p.parse_args(argv)
    validate_args(args)
    return args


def validate_args(args) -> None:
    if args.service_discovery == "static":
        if not args.static_backends:
            raise ValueError("static discovery requires --static-backends")
        if not args.static_models:
            raise ValueError("static discovery requires --static-models")
        n_backends = len(args.static_backends.split(","))
        n_models = len(args.static_models.split(","))
        if n_backends != n_models:
            raise ValueError(
                f"--static-backends ({n_backends}) and --static-models ({n_models}) "
                "must have the same length"
            )
    if not 0.0 <= args.trace_sample_rate <= 1.0:
        raise ValueError("--trace-sample-rate must be in [0, 1]")
    if not 0.0 <= args.batch_avoid_attainment <= 1.0:
        raise ValueError("--batch-avoid-attainment must be in [0, 1]")
    if args.retry_max_attempts < 1:
        raise ValueError("--retry-max-attempts must be >= 1")
    if args.retry_backoff_base < 0 or args.retry_backoff_max < 0:
        raise ValueError("--retry-backoff-base/--retry-backoff-max must be >= 0")
    for flag in ("deadline_request", "deadline_ttft", "deadline_inter_chunk",
                 "breaker_cooldown"):
        if getattr(args, flag) < 0:
            raise ValueError(f"--{flag.replace('_', '-')} must be >= 0 (0 disables)")
    if args.trace_buffer_size < 1:
        raise ValueError("--trace-buffer-size must be >= 1")
    if args.slo_ttft_ms <= 0 or args.slo_itl_ms <= 0:
        raise ValueError("--slo-ttft-ms/--slo-itl-ms must be > 0")
    if args.saturation_queue_ref < 1:
        raise ValueError("--saturation-queue-ref must be >= 1")
    if args.routing_logic == "session" and not args.session_key:
        raise ValueError("session routing requires --session-key")
    if args.routing_logic == "kvaware" and not (
        args.kv_controller_url or args.kv_directory_url
    ):
        raise ValueError(
            "kvaware routing requires --kv-controller-url or "
            "--kv-directory-url"
        )
    if args.routing_logic == "disaggregated_prefill" and not (
        args.prefill_model_labels and args.decode_model_labels
    ):
        raise ValueError(
            "disaggregated_prefill requires --prefill-model-labels and "
            "--decode-model-labels"
        )
