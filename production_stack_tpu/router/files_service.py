"""OpenAI Files API backing store (local disk).

Parity: src/vllm_router/services/files_service/ in /root/reference
(FileStorage file_storage.py:27-136, OpenAIFile). Async file IO via
asyncio.to_thread (aiofiles is not in this environment).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
import uuid
from dataclasses import asdict, dataclass
from typing import Optional


@dataclass
class OpenAIFile:
    id: str
    bytes: int
    created_at: int
    filename: str
    object: str = "file"
    purpose: str = "batch"

    def metadata(self) -> dict:
        return asdict(self)


class FileStorage:
    def __init__(self, base_path: str = "/tmp/tpu_router_files"):
        self.base_path = base_path
        os.makedirs(base_path, exist_ok=True)

    def _dir(self, file_id: str) -> str:
        return os.path.join(self.base_path, file_id)

    async def save_file(
        self, content: bytes, filename: str, purpose: str = "batch",
        file_id: Optional[str] = None,
    ) -> OpenAIFile:
        file_id = file_id or f"file-{uuid.uuid4().hex}"
        f = OpenAIFile(
            id=file_id, bytes=len(content), created_at=int(time.time()),
            filename=filename, purpose=purpose,
        )

        def _write():
            os.makedirs(self._dir(file_id), exist_ok=True)
            with open(os.path.join(self._dir(file_id), filename), "wb") as fh:
                fh.write(content)
            with open(os.path.join(self._dir(file_id), "metadata.json"), "w") as fh:
                json.dump(f.metadata(), fh)

        await asyncio.to_thread(_write)
        return f

    async def get_file(self, file_id: str) -> OpenAIFile:
        def _read():
            with open(os.path.join(self._dir(file_id), "metadata.json")) as fh:
                return OpenAIFile(**json.load(fh))

        try:
            return await asyncio.to_thread(_read)
        except FileNotFoundError:
            raise KeyError(file_id)

    async def get_file_content(self, file_id: str) -> bytes:
        meta = await self.get_file(file_id)

        def _read():
            with open(os.path.join(self._dir(file_id), meta.filename), "rb") as fh:
                return fh.read()

        return await asyncio.to_thread(_read)

    async def list_files(self) -> list[OpenAIFile]:
        out = []
        for fid in sorted(os.listdir(self.base_path)):
            try:
                out.append(await self.get_file(fid))
            except (KeyError, json.JSONDecodeError):
                continue
        return out

    async def delete_file(self, file_id: str) -> None:
        import shutil

        await asyncio.to_thread(shutil.rmtree, self._dir(file_id), True)


_storage: Optional[FileStorage] = None


def initialize_storage(base_path: str) -> FileStorage:
    global _storage
    _storage = FileStorage(base_path)
    return _storage


def get_storage() -> FileStorage:
    assert _storage is not None, "file storage not initialized"
    return _storage
