"""OpenAI Batch API: SQLite-backed queue + background processor.

Parity: src/vllm_router/services/batch_service/ in /root/reference
(BatchProcessor processor.py:21-58, BatchInfo/BatchStatus batch.py:19-103,
LocalBatchProcessor local_processor.py:32-221). sqlite3 runs in a thread
(aiosqlite is not in this environment).
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

import aiohttp

from production_stack_tpu.router.files_service import FileStorage
from production_stack_tpu.router.utils import cancel_task
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class BatchStatus:
    VALIDATING = "validating"
    IN_PROGRESS = "in_progress"
    COMPLETED = "completed"
    FAILED = "failed"
    CANCELLED = "cancelled"


@dataclass
class BatchInfo:
    id: str
    input_file_id: str
    endpoint: str
    completion_window: str
    status: str = BatchStatus.VALIDATING
    created_at: int = field(default_factory=lambda: int(time.time()))
    output_file_id: Optional[str] = None
    error_file_id: Optional[str] = None
    request_counts: dict = field(default_factory=lambda: {"total": 0, "completed": 0, "failed": 0})
    metadata: Optional[dict] = None

    def to_dict(self) -> dict:
        return {
            "id": self.id, "object": "batch", "endpoint": self.endpoint,
            "input_file_id": self.input_file_id,
            "completion_window": self.completion_window, "status": self.status,
            "created_at": self.created_at, "output_file_id": self.output_file_id,
            "error_file_id": self.error_file_id, "request_counts": self.request_counts,
            "metadata": self.metadata,
        }


class LocalBatchProcessor:
    """Processes batches by sending each line's request through the router's
    own HTTP endpoint (so routing logic applies per batch line)."""

    def __init__(self, db_path: str, storage: FileStorage, router_base_url: str):
        self.db_path = db_path
        self.storage = storage
        self.router_base_url = router_base_url
        self._queue: asyncio.Queue[str] = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._db_lock = asyncio.Lock()
        self._init_db()

    def _init_db(self) -> None:
        with sqlite3.connect(self.db_path) as db:
            db.execute(
                "CREATE TABLE IF NOT EXISTS batches (id TEXT PRIMARY KEY, data TEXT)"
            )

    async def _db(self, fn):
        async with self._db_lock:
            return await asyncio.to_thread(fn)

    async def _save(self, info: BatchInfo) -> None:
        def _w():
            with sqlite3.connect(self.db_path) as db:
                db.execute(
                    "INSERT OR REPLACE INTO batches VALUES (?, ?)",
                    (info.id, json.dumps(info.to_dict())),
                )

        await self._db(_w)

    async def start(self) -> None:
        self._task = asyncio.create_task(self._worker())
        # resume unfinished batches after restart (checkpoint/resume parity)
        for info in await self.list_batches():
            if info.status in (BatchStatus.VALIDATING, BatchStatus.IN_PROGRESS):
                await self._queue.put(info.id)

    async def close(self) -> None:
        if self._task:
            await cancel_task(self._task)
            self._task = None

    async def create_batch(
        self, input_file_id: str, endpoint: str, completion_window: str,
        metadata: Optional[dict] = None,
    ) -> BatchInfo:
        info = BatchInfo(
            id=f"batch_{uuid.uuid4().hex}", input_file_id=input_file_id,
            endpoint=endpoint, completion_window=completion_window, metadata=metadata,
        )
        await self._save(info)
        await self._queue.put(info.id)
        return info

    async def retrieve_batch(self, batch_id: str) -> BatchInfo:
        def _r():
            with sqlite3.connect(self.db_path) as db:
                row = db.execute(
                    "SELECT data FROM batches WHERE id = ?", (batch_id,)
                ).fetchone()
            return row

        row = await self._db(_r)
        if row is None:
            raise KeyError(batch_id)
        d = json.loads(row[0])
        d.pop("object", None)
        return BatchInfo(**d)

    async def list_batches(self) -> list[BatchInfo]:
        def _r():
            with sqlite3.connect(self.db_path) as db:
                return db.execute("SELECT data FROM batches").fetchall()

        rows = await self._db(_r)
        out = []
        for (data,) in rows:
            d = json.loads(data)
            d.pop("object", None)
            out.append(BatchInfo(**d))
        return sorted(out, key=lambda b: b.created_at, reverse=True)

    async def cancel_batch(self, batch_id: str) -> BatchInfo:
        info = await self.retrieve_batch(batch_id)
        if info.status in (BatchStatus.VALIDATING, BatchStatus.IN_PROGRESS):
            info.status = BatchStatus.CANCELLED
            await self._save(info)
        return info

    async def _worker(self) -> None:
        while True:
            batch_id = await self._queue.get()
            try:
                await self._process(batch_id)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("batch %s failed", batch_id)
                try:
                    info = await self.retrieve_batch(batch_id)
                    info.status = BatchStatus.FAILED
                    await self._save(info)
                except KeyError:
                    pass

    async def _process(self, batch_id: str) -> None:
        info = await self.retrieve_batch(batch_id)
        if info.status == BatchStatus.CANCELLED:
            return
        content = await self.storage.get_file_content(info.input_file_id)
        lines = [l for l in content.decode().splitlines() if l.strip()]
        info.status = BatchStatus.IN_PROGRESS
        info.request_counts["total"] = len(lines)
        await self._save(info)
        results = []
        async with aiohttp.ClientSession() as session:
            for line in lines:
                info = await self.retrieve_batch(batch_id)
                if info.status == BatchStatus.CANCELLED:
                    return
                try:
                    req = json.loads(line)
                    async with session.post(
                        f"{self.router_base_url}{req.get('url', info.endpoint)}",
                        json=req.get("body", {}),
                    ) as resp:
                        body = await resp.json()
                        ok = resp.status == 200
                    results.append(
                        {
                            "id": f"batch_req_{uuid.uuid4().hex[:12]}",
                            "custom_id": req.get("custom_id"),
                            "response": {"status_code": resp.status, "body": body},
                            "error": None if ok else {"message": str(body)},
                        }
                    )
                    info.request_counts["completed" if ok else "failed"] += 1
                except Exception as e:
                    results.append(
                        {
                            "id": f"batch_req_{uuid.uuid4().hex[:12]}",
                            "custom_id": None,
                            "response": None,
                            "error": {"message": str(e)},
                        }
                    )
                    info.request_counts["failed"] += 1
                await self._save(info)
        out = "\n".join(json.dumps(r) for r in results).encode()
        f = await self.storage.save_file(out, "output.jsonl", purpose="batch_output")
        info.output_file_id = f.id
        info.status = BatchStatus.COMPLETED
        await self._save(info)
        logger.info("batch %s completed: %s", batch_id, info.request_counts)


_processor: Optional[LocalBatchProcessor] = None


def initialize_batch_processor(
    db_path: str, storage: FileStorage, router_base_url: str
) -> LocalBatchProcessor:
    global _processor
    _processor = LocalBatchProcessor(db_path, storage, router_base_url)
    return _processor


def get_batch_processor() -> LocalBatchProcessor:
    assert _processor is not None, "batch processor not initialized"
    return _processor
