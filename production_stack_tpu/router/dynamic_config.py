"""Dynamic (hot-reload) router configuration.

Parity: src/vllm_router/dynamic_config.py in /root/reference —
DynamicRouterConfig :38-96, DynamicConfigWatcher polling loop :200-219,
reconfigure_* :133-188. Watches a JSON file (a mounted ConfigMap in K8s) and
live-swaps service discovery and routing logic.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
from typing import Optional

from production_stack_tpu.router import routing_logic as rl
from production_stack_tpu.router import service_discovery as sd
from production_stack_tpu.router.utils import cancel_task, parse_comma_separated
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


@dataclasses.dataclass
class DynamicRouterConfig:
    service_discovery: Optional[str] = None
    static_backends: Optional[str] = None
    static_models: Optional[str] = None
    routing_logic: Optional[str] = None
    session_key: Optional[str] = None
    kv_controller_url: Optional[str] = None
    kv_directory_url: Optional[str] = None
    prefill_model_labels: Optional[str] = None
    decode_model_labels: Optional[str] = None

    @staticmethod
    def from_json(path: str) -> "DynamicRouterConfig":
        with open(path) as f:
            data = json.load(f)
        fields = {f.name for f in dataclasses.fields(DynamicRouterConfig)}
        return DynamicRouterConfig(**{k: v for k, v in data.items() if k in fields})

    def to_json_str(self) -> str:
        return json.dumps(dataclasses.asdict(self))


class DynamicConfigWatcher:
    _instance: Optional["DynamicConfigWatcher"] = None

    def __init__(self, config_path: str, poll_interval: float = 10.0):
        self.config_path = config_path
        self.poll_interval = poll_interval
        self.current: Optional[DynamicRouterConfig] = None
        self._mtime: float = 0.0
        self._task: Optional[asyncio.Task] = None
        DynamicConfigWatcher._instance = self

    async def start(self) -> None:
        self._task = asyncio.create_task(self._watch())

    async def close(self) -> None:
        if self._task:
            await cancel_task(self._task)
            self._task = None

    async def _watch(self) -> None:
        while True:
            try:
                mtime = os.path.getmtime(self.config_path)
                if mtime != self._mtime:
                    self._mtime = mtime
                    # config read off the loop: a ConfigMap mount mid-update
                    # (or any slow volume) must not stall in-flight streaming
                    # proxies for the duration of a sync read (GC001)
                    cfg = await asyncio.to_thread(
                        DynamicRouterConfig.from_json, self.config_path
                    )
                    await self._apply(cfg)
            except FileNotFoundError:
                pass
            except Exception:
                logger.exception("dynamic config reload failed")
            await asyncio.sleep(self.poll_interval)

    async def _apply(self, cfg: DynamicRouterConfig) -> None:
        logger.info("applying dynamic config: %s", cfg.to_json_str())
        if cfg.service_discovery == "static" and cfg.static_backends:
            old = sd._global_service_discovery
            new = sd.StaticServiceDiscovery(
                urls=parse_comma_separated(cfg.static_backends),
                models=parse_comma_separated(cfg.static_models),
            )
            sd.set_service_discovery(new)
            if old is not None:
                await old.close()
        if cfg.routing_logic:
            rl.reconfigure_routing_logic(
                cfg.routing_logic,
                session_key=cfg.session_key,
                kv_controller_url=cfg.kv_controller_url,
                kv_directory_url=cfg.kv_directory_url,
                prefill_model_labels=parse_comma_separated(cfg.prefill_model_labels),
                decode_model_labels=parse_comma_separated(cfg.decode_model_labels),
            )
        self.current = cfg

    @staticmethod
    def get() -> Optional["DynamicConfigWatcher"]:
        return DynamicConfigWatcher._instance
