"""Chunked hash trie for prefix-aware routing.

Parity: src/vllm_router/prefix/hashtrie.py in /root/reference (chunk size 128
chars :36, insert :58, longest_prefix_match :76-103). blake2b replaces xxhash
(not in this environment); same structure: each trie level keys on the hash of
one 128-char chunk, nodes remember which endpoints have seen that prefix.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Optional


def _chunk_hash(chunk: str) -> int:
    return int.from_bytes(hashlib.blake2b(chunk.encode(), digest_size=8).digest(), "little")


class TrieNode:
    __slots__ = ("children", "endpoints", "lock")

    def __init__(self):
        self.children: dict[int, TrieNode] = {}
        self.endpoints: set[str] = set()
        self.lock = asyncio.Lock()


class HashTrie:
    def __init__(self, chunk_size: int = 128):
        self.root = TrieNode()
        self.chunk_size = chunk_size

    def _chunks(self, text: str):
        for i in range(0, len(text), self.chunk_size):
            yield _chunk_hash(text[i : i + self.chunk_size])

    async def insert(self, text: str, endpoint: str) -> None:
        node = self.root
        async with node.lock:
            node.endpoints.add(endpoint)
        for h in self._chunks(text):
            async with node.lock:
                nxt = node.children.get(h)
                if nxt is None:
                    nxt = node.children[h] = TrieNode()
            async with nxt.lock:
                nxt.endpoints.add(endpoint)
            node = nxt

    async def longest_prefix_match(
        self, text: str, available: Optional[set[str]] = None
    ) -> tuple[int, set[str]]:
        """Returns (matched_chars, endpoints at the deepest matched node,
        filtered by `available`)."""
        node = self.root
        matched = 0
        selected: set[str] = set()
        for i, h in enumerate(self._chunks(text)):
            nxt = node.children.get(h)
            if nxt is None:
                break
            eps = nxt.endpoints if available is None else (nxt.endpoints & available)
            if not eps:
                break
            matched = min((i + 1) * self.chunk_size, len(text))
            selected = set(eps)
            node = nxt
        if not selected and available:
            selected = set(available)
        return matched, selected

    async def remove_endpoint(self, endpoint: str) -> None:
        stack = [self.root]
        while stack:
            node = stack.pop()
            node.endpoints.discard(endpoint)
            stack.extend(node.children.values())
