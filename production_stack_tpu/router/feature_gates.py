"""Experimental feature gates.

Parity: src/vllm_router/experimental/feature_gates.py:46-108 in /root/reference
(`--feature-gates SemanticCache=true,PIIDetection=true`).
"""

from __future__ import annotations

from typing import Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

KNOWN_FEATURES = {"SemanticCache", "PIIDetection"}


class FeatureGates:
    def __init__(self, spec: str = ""):
        self.enabled: set[str] = set()
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, value = part.partition("=")
            if name not in KNOWN_FEATURES:
                raise ValueError(
                    f"unknown feature gate {name!r}; known: {sorted(KNOWN_FEATURES)}"
                )
            if value.lower() in ("true", "1", "yes"):
                self.enabled.add(name)
        if self.enabled:
            logger.info("enabled experimental features: %s", sorted(self.enabled))

    def is_enabled(self, name: str) -> bool:
        return name in self.enabled


_gates = FeatureGates()


def initialize_feature_gates(spec: str) -> FeatureGates:
    global _gates
    _gates = FeatureGates(spec)
    return _gates


def get_feature_gates() -> FeatureGates:
    return _gates
