"""Router application: bootstrap + HTTP surface.

Parity: src/vllm_router/app.py (initialize_all :107-242, main :265-285) and
routers/main_router.py + metrics_router.py + files_router.py +
batches_router.py in /root/reference. aiohttp replaces FastAPI/uvicorn.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

import psutil
from aiohttp import web

from production_stack_tpu import __version__
from production_stack_tpu.router import batch_service, files_service
from production_stack_tpu.router.callbacks import get_callbacks, load_callbacks
from production_stack_tpu.router.dynamic_config import DynamicConfigWatcher
from production_stack_tpu.router.engine_stats import (
    get_engine_stats_scraper,
    initialize_engine_stats_scraper,
)
from production_stack_tpu.router.feature_gates import get_feature_gates, initialize_feature_gates
from production_stack_tpu.router.parser import parse_args
from production_stack_tpu.router.request_service import (
    close_client_session,
    route_general_request,
    route_sleep_wakeup_request,
)
from production_stack_tpu.router.resilience import (
    initialize_resilience,
    render_resilience_metrics,
)
from production_stack_tpu.router.request_stats import (
    get_request_stats_monitor,
    initialize_request_stats_monitor,
)
from production_stack_tpu.router.routing_logic import initialize_routing_logic
from production_stack_tpu.router.service_discovery import (
    get_service_discovery,
    initialize_service_discovery,
)
from production_stack_tpu.router.utils import parse_comma_separated, set_ulimit
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


class RouterApp:
    def __init__(self, args):
        self.args = args
        self.model_aliases: Optional[dict] = (
            json.loads(args.model_aliases) if args.model_aliases else None
        )
        self._bg: list = []
        self.semantic_cache = None
        self.pii_analyzer = None
        # graceful drain: health flips 503 (LB pulls the pod) while aiohttp's
        # shutdown drains in-flight streaming proxies
        self.draining = False

    # -- bootstrap (parity app.py:initialize_all) ---------------------------

    async def initialize_all(self) -> None:
        args = self.args
        if getattr(args, "sentry_dsn", None):
            # error reporting parity (reference app.py:118-119). The SDK is
            # optional in this environment; the flag degrades gracefully.
            try:
                import sentry_sdk

                sentry_sdk.init(dsn=args.sentry_dsn, traces_sample_rate=0.1)
                logger.info("sentry error reporting initialized")
            except ImportError:
                logger.warning(
                    "--sentry-dsn set but sentry_sdk is not installed; "
                    "error reporting disabled"
                )
            except Exception as e:  # noqa: BLE001 - e.g. BadDsn
                # a typo'd DSN must not crash-loop the router pod
                logger.warning("sentry init failed (%s); error reporting disabled", e)
        if args.service_discovery == "static":
            sd = initialize_service_discovery(
                "static",
                urls=parse_comma_separated(args.static_backends),
                models=parse_comma_separated(args.static_models),
                aliases=parse_comma_separated(args.static_aliases) or None,
                model_labels=parse_comma_separated(args.static_model_labels) or None,
                model_types=parse_comma_separated(args.static_model_types) or None,
                static_backend_health_checks=args.static_backend_health_checks,
                health_check_interval=args.health_check_interval,
            )
        else:
            sd = initialize_service_discovery(
                "k8s",
                namespace=args.k8s_namespace,
                label_selector=args.k8s_label_selector,
                port=args.k8s_port,
                prefill_model_labels=parse_comma_separated(args.prefill_model_labels),
                decode_model_labels=parse_comma_separated(args.decode_model_labels),
            )
        await sd.start()
        initialize_resilience(
            retry_max_attempts=getattr(args, "retry_max_attempts", 3),
            retry_backoff_base=getattr(args, "retry_backoff_base", 0.05),
            retry_backoff_max=getattr(args, "retry_backoff_max", 2.0),
            deadline_request=getattr(args, "deadline_request", 0.0),
            deadline_ttft=getattr(args, "deadline_ttft", 0.0),
            deadline_inter_chunk=getattr(args, "deadline_inter_chunk", 0.0),
            breaker_failure_threshold=getattr(args, "breaker_failure_threshold", 5),
            breaker_cooldown=getattr(args, "breaker_cooldown", 30.0),
        )
        scraper = initialize_engine_stats_scraper(args.engine_stats_interval)
        await scraper.start()
        initialize_request_stats_monitor(args.request_stats_window)
        from production_stack_tpu.router.slo import initialize_slo_monitor

        initialize_slo_monitor(
            ttft_ms=getattr(args, "slo_ttft_ms", 2000.0),
            itl_ms=getattr(args, "slo_itl_ms", 200.0),
            saturation_queue_ref=getattr(args, "saturation_queue_ref", 8),
        )
        from production_stack_tpu.router.request_service import (
            set_batch_avoid_attainment,
        )

        set_batch_avoid_attainment(
            getattr(args, "batch_avoid_attainment", 0.9)
        )
        initialize_routing_logic(
            args.routing_logic,
            session_key=args.session_key,
            kv_controller_url=args.kv_controller_url,
            kv_directory_url=getattr(args, "kv_directory_url", None),
            tokenizer_path=args.tokenizer,
            prefill_model_labels=parse_comma_separated(args.prefill_model_labels),
            decode_model_labels=parse_comma_separated(args.decode_model_labels),
        )
        if args.callbacks:
            load_callbacks(args.callbacks)
        initialize_feature_gates(args.feature_gates)
        from production_stack_tpu.tracing import configure_tracing

        configure_tracing(
            sample_rate=getattr(args, "trace_sample_rate", 1.0),
            capacity=getattr(args, "trace_buffer_size", None),
        )
        if get_feature_gates().is_enabled("SemanticCache"):
            from production_stack_tpu.router import semantic_cache as sc

            choice = getattr(args, "semantic_cache_embedder", "auto")
            if choice == "ngram":
                embed = sc.ngram_hash_embed
            elif choice == "sentence-transformers":
                embed = sc.SentenceTransformerEmbedder()  # raises if absent
            else:  # auto: real encoder when installed + cached, else n-grams
                embed = None
            self.semantic_cache = sc.SemanticCache(
                threshold=args.semantic_cache_threshold, embed=embed
            )
        if get_feature_gates().is_enabled("PIIDetection"):
            from production_stack_tpu.router.pii import make_analyzer

            # built ONCE at startup: the Presidio tier loads an NER model —
            # seconds of work that must not land on the first request
            self.pii_analyzer = make_analyzer(
                getattr(args, "pii_analyzer", "auto")
            )
        files_service.initialize_storage(args.file_storage_path)
        if args.enable_batch_api:
            proc = batch_service.initialize_batch_processor(
                args.batch_db_path,
                files_service.get_storage(),
                f"http://127.0.0.1:{args.port}",
            )
            await proc.start()
        if args.dynamic_config_json:
            watcher = DynamicConfigWatcher(args.dynamic_config_json)
            await watcher.start()
        if args.log_stats:
            self._bg.append(asyncio.create_task(self._log_stats_loop()))

    async def _log_stats_loop(self) -> None:
        """Periodic human-readable stats dump (parity stats/log_stats.py:37-115)."""
        while True:
            await asyncio.sleep(self.args.log_stats_interval)
            try:
                stats = get_request_stats_monitor().get_request_stats()
                engine = get_engine_stats_scraper().get_engine_stats()
                lines = ["", "==================== Router Stats ===================="]
                for url in sorted(set(stats) | set(engine)):
                    rs = stats.get(url)
                    es = engine.get(url)
                    lines.append(f"  {url}:")
                    if rs:
                        lines.append(
                            f"    qps={rs.qps:.2f} ttft={rs.ttft:.3f}s "
                            f"prefill={rs.in_prefill_requests} "
                            f"decode={rs.in_decoding_requests} "
                            f"finished={rs.finished_requests} itl={rs.avg_itl:.4f}"
                        )
                    if es:
                        lines.append(
                            f"    running={es.num_running_requests} "
                            f"waiting={es.num_queuing_requests} "
                            f"kv_usage={es.gpu_cache_usage_perc:.1%} "
                            f"kv_hit_rate={es.gpu_prefix_cache_hit_rate:.1%}"
                        )
                lines.append("======================================================")
                logger.info("\n".join(lines))
            except Exception:
                logger.exception("log stats failed")

    # -- handlers -----------------------------------------------------------

    async def _proxy(self, request: web.Request) -> web.StreamResponse:
        endpoint = request.path
        body = await request.read()
        try:
            request_json = json.loads(body) if body else {}
        except json.JSONDecodeError:
            request_json = {}
        cb = get_callbacks()
        if cb is not None:
            short = cb.pre_request(request, body, request_json)
            if short is not None:
                status, payload = short
                return web.json_response(payload, status=status)
        if self.pii_analyzer is not None:
            # NER analysis (Presidio tier) is CPU-bound: keep it off the
            # event loop so concurrent streams don't stall behind it
            blocked, body = await asyncio.get_event_loop().run_in_executor(
                None, self._apply_pii_policy, body, request_json
            )
            if blocked is not None:
                return blocked
        if self.semantic_cache is not None and endpoint == "/v1/chat/completions":
            hit = await self.semantic_cache.check(body)
            if hit is not None:
                return web.json_response(hit, headers={"X-Semantic-Cache": "hit"})

        capture = None
        wants_cache = (
            self.semantic_cache is not None
            and endpoint == "/v1/chat/completions"
            and not request_json.get("stream")
        )
        if wants_cache or (cb is not None):
            req_body = body

            async def capture(status: int, resp_body: bytes):
                if cb is not None:
                    try:
                        cb.post_request(request, resp_body)
                    except Exception:
                        logger.exception("post_request callback failed")
                if wants_cache and status == 200:
                    try:
                        await self.semantic_cache.store(req_body, json.loads(resp_body))
                    except json.JSONDecodeError:
                        pass

        return await route_general_request(
            request, endpoint, model_aliases=self.model_aliases,
            capture_body=capture, body_override=body,
        )

    def _apply_pii_policy(self, body: bytes, request_json: dict):
        """Scan prompt/messages for PII; redact or block per --pii-policy.
        Parity: experimental/pii/middleware.py:43-154 in /root/reference."""
        from production_stack_tpu.router.pii import check_pii_content, redact

        analyzer = self.pii_analyzer
        texts = []
        if isinstance(request_json.get("prompt"), str):
            texts.append(request_json["prompt"])
        for m in request_json.get("messages", []) or []:
            if isinstance(m, dict) and isinstance(m.get("content"), str):
                texts.append(m["content"])
        matches = [m for t in texts for m in check_pii_content(t, analyzer)]
        if not matches:
            return None, body
        kinds = sorted({m.kind for m in matches})
        if self.args.pii_policy == "block":
            logger.warning("blocking request containing PII: %s", kinds)
            return (
                web.json_response(
                    {"error": {"message": f"request contains PII: {kinds}"}}, status=400
                ),
                body,
            )
        logger.info("redacting PII from request: %s", kinds)
        if isinstance(request_json.get("prompt"), str):
            request_json["prompt"] = redact(request_json["prompt"], analyzer=analyzer)
        for m in request_json.get("messages", []) or []:
            if isinstance(m, dict) and isinstance(m.get("content"), str):
                m["content"] = redact(m["content"], analyzer=analyzer)
        return None, json.dumps(request_json).encode()

    async def models(self, request: web.Request) -> web.Response:
        sd = get_service_discovery()
        seen: dict[str, dict] = {}
        for ep in sd.get_endpoint_info():
            for name in ep.model_names:
                info = ep.model_info.get(name) if ep.model_info else None
                seen.setdefault(
                    name,
                    info
                    or {
                        "id": name,
                        "object": "model",
                        "created": int(ep.added_timestamp),
                        "owned_by": "production-stack-tpu",
                    },
                )
        if self.model_aliases:
            for alias, target in self.model_aliases.items():
                if target in seen and alias not in seen:
                    aliased = dict(seen[target])
                    aliased["id"] = alias
                    seen[alias] = aliased
        return web.json_response({"object": "list", "data": list(seen.values())})

    async def health(self, request: web.Request) -> web.Response:
        if self.draining:
            return web.json_response({"status": "draining"}, status=503)
        sd = get_service_discovery()
        scraper = get_engine_stats_scraper()
        if not sd.get_health():
            return web.json_response({"status": "unhealthy: service discovery"}, status=503)
        if not scraper.get_health():
            return web.json_response({"status": "unhealthy: stats scraper"}, status=503)
        watcher = DynamicConfigWatcher.get()
        payload = {"status": "healthy"}
        if watcher and watcher.current:
            payload["dynamic_config"] = json.loads(watcher.current.to_json_str())
        return web.json_response(payload)

    async def engines(self, request: web.Request) -> web.Response:
        from production_stack_tpu.router.resilience import get_breaker_registry

        sd = get_service_discovery()
        out = []
        stats = get_engine_stats_scraper().get_engine_stats()
        rstats = get_request_stats_monitor().get_request_stats()
        breakers = get_breaker_registry().states()
        for ep in sd.get_endpoint_info():
            d = {
                "url": ep.url,
                "models": ep.model_names,
                "model_label": ep.model_label,
                "sleep": ep.sleep,
                "added": ep.added_timestamp,
            }
            b = breakers.get(ep.url)
            if b is not None:
                d["breaker"] = b.state_name
            es = stats.get(ep.url)
            if es:
                d["engine_stats"] = es.__dict__
            rs = rstats.get(ep.url)
            if rs:
                d["request_stats"] = rs.__dict__
            out.append(d)
        # active-check failures + open breakers: the pulled-from-rotation set
        # (the breaker integration in service_discovery surfaces here)
        return web.json_response(
            {"engines": out, "unhealthy": sd.get_unhealthy_endpoint_urls()}
        )

    async def version(self, request: web.Request) -> web.Response:
        return web.json_response({"version": __version__})

    async def metrics(self, request: web.Request) -> web.Response:
        """Router Prometheus metrics (parity routers/metrics_router.py:57-123)."""
        lines = []

        def gauge(name, value, labels=""):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{labels} {value}")

        proc = psutil.Process()
        gauge("vllm_router:cpu_usage_perc", psutil.cpu_percent() / 100.0)
        gauge("vllm_router:memory_usage_bytes", proc.memory_info().rss)
        disk = psutil.disk_usage("/")
        gauge("vllm_router:disk_usage_perc", disk.percent / 100.0)
        rstats = get_request_stats_monitor().get_request_stats()
        for url, rs in rstats.items():
            lab = f'{{server="{url}"}}'
            gauge("vllm_router:current_qps", rs.qps, lab)
            gauge("vllm_router:avg_ttft", rs.ttft, lab)
            gauge("vllm_router:in_prefill_requests", rs.in_prefill_requests, lab)
            gauge("vllm_router:in_decoding_requests", rs.in_decoding_requests, lab)
            gauge("vllm_router:finished_requests", rs.finished_requests, lab)
            gauge("vllm_router:avg_latency", rs.avg_latency, lab)
            gauge("vllm_router:avg_itl", rs.avg_itl, lab)
            gauge("vllm_router:num_swapped_requests", rs.num_swapped_requests, lab)
        estats = get_engine_stats_scraper().get_engine_stats()
        for url, es in estats.items():
            lab = f'{{server="{url}"}}'
            gauge("vllm_router:engine_running_requests", es.num_running_requests, lab)
            gauge("vllm_router:engine_waiting_requests", es.num_queuing_requests, lab)
            gauge("vllm_router:gpu_cache_usage_perc", es.gpu_cache_usage_perc, lab)
            gauge("vllm_router:gpu_prefix_cache_hit_rate", es.gpu_prefix_cache_hit_rate, lab)
        # failure-domain layer: vllm_router:retries_total,
        # vllm_router:failovers_total, vllm_router:deadline_aborts_total,
        # per-backend vllm_router:circuit_state (0=closed 1=half-open 2=open)
        # and vllm_router:circuit_open_events_total
        lines.extend(render_resilience_metrics())
        # KV-aware v2 route-class mix (docs/kv-directory.md):
        # vllm_router:kvaware_v2_{resident,restorable,cold}_routes_total
        # plus the disagg decode picks scored by fabric transfer cost
        # (docs/kv-fabric.md): vllm_router:disagg_fabric_routes_total
        from production_stack_tpu.router.routing_logic import (
            render_kvaware_metrics,
        )

        lines.extend(render_kvaware_metrics())
        # SLO accounting (router/slo.py): vllm_router:slo_attained_total /
        # vllm_router:slo_violated_total per (objective, model, server),
        # vllm_router:slo_request_outcomes_total, vllm_router:slo_records_total,
        # and the vllm_router:fleet_saturation autoscaling gauge (computed
        # fresh per scrape from the live engine stats + shed windows)
        from production_stack_tpu.router.resilience import get_saturation_registry
        from production_stack_tpu.router.slo import get_slo_monitor

        slo = get_slo_monitor()
        sat = get_saturation_registry()
        shedding = [url for url in estats if sat.is_saturated(url)]
        lines.extend(
            slo.render(
                fleet_saturation=slo.fleet_saturation(estats, shedding)
            )
        )
        # per-hop TTFT breakdown (receive->route->backend-headers->first
        # chunk): attributes tail latency to a stage instead of "the stack".
        # One TYPE line per metric name (duplicates fail the whole scrape).
        from production_stack_tpu.router.request_service import get_hop_quantiles

        for hop, qs in get_hop_quantiles().items():
            name = f"vllm_router:ttft_hop_{hop}_ms"
            lines.append(f"# TYPE {name} gauge")
            for q, v in qs.items():
                lines.append(f'{name}{{quantile="{q}"}} {round(v, 3)}')
        # TTFT / e2e-latency distribution histograms (dashboard heatmaps)
        from production_stack_tpu.router.request_service import (
            latency_hist,
            ttft_hist,
        )

        lines.extend(ttft_hist.render('source="router"'))
        lines.extend(latency_hist.render('source="router"'))
        # per-phase histograms (tracing subsystem): the engine observes
        # these; a router-only process exposes them zero-count so either
        # scrape job satisfies the dashboard. In a co-hosted process
        # (bench.py) both endpoints render the same process-global counts
        # under different labels, so the dashboard's phase panels filter on
        # model_name!="" to count the engine's series exactly once
        from production_stack_tpu.tracing import (
            render_collector_metrics,
            render_phase_histograms,
        )

        lines.extend(render_phase_histograms('source="router"'))
        # span-loss visibility for THIS process's collector (satellite of
        # ISSUE 7): ring-wrap overwrites and head-sampling rejections are
        # silent by design — the counters make the loss measurable before
        # someone debugs a tail with an incomplete trace
        lines.extend(render_collector_metrics('source="router"'))
        return web.Response(text="\n".join(lines) + "\n", content_type="text/plain")

    async def traces(self, request: web.Request) -> web.Response:
        """Span ring-buffer export (read-only debug surface; docs/tracing.md).
        ?trace_id= filters to one trace, ?limit= caps the trace count."""
        from production_stack_tpu.tracing import export_for_query

        payload, status = export_for_query(request.query)
        return web.json_response(payload, status=status)

    async def metrics_reset(self, request: web.Request) -> web.Response:
        """Clear the TTFT hop sample window (debug/bench endpoint) so a
        benchmark phase's hop quantiles describe only that phase."""
        from production_stack_tpu.router.request_service import reset_hop_samples
        from production_stack_tpu.router.resilience import reset_counters
        from production_stack_tpu.tracing import get_collector

        reset_hop_samples()
        reset_counters()
        # per-phase bench windows: traces too, so a phase's attribution table
        # describes only that phase's requests
        get_collector().reset()
        return web.json_response({"status": "ok"})

    # -- files & batches (parity files_router.py, batches_router.py) --------

    async def upload_file(self, request: web.Request) -> web.Response:
        reader = await request.multipart()
        purpose, filename, content = "batch", "upload", b""
        async for part in reader:
            if part.name == "purpose":
                purpose = (await part.text()).strip()
            elif part.name == "file":
                filename = part.filename or "upload"
                content = await part.read()
        f = await files_service.get_storage().save_file(content, filename, purpose)
        return web.json_response(f.metadata())

    async def list_files(self, request: web.Request) -> web.Response:
        files = await files_service.get_storage().list_files()
        return web.json_response(
            {"object": "list", "data": [f.metadata() for f in files]}
        )

    async def get_file(self, request: web.Request) -> web.Response:
        try:
            f = await files_service.get_storage().get_file(request.match_info["file_id"])
        except KeyError:
            return web.json_response({"error": "file not found"}, status=404)
        return web.json_response(f.metadata())

    async def get_file_content(self, request: web.Request) -> web.Response:
        try:
            content = await files_service.get_storage().get_file_content(
                request.match_info["file_id"]
            )
        except (KeyError, FileNotFoundError):
            return web.json_response({"error": "file not found"}, status=404)
        return web.Response(body=content, content_type="application/octet-stream")

    async def create_batch(self, request: web.Request) -> web.Response:
        if not self.args.enable_batch_api:
            return web.json_response({"error": "batch API disabled"}, status=400)
        body = await request.json()
        info = await batch_service.get_batch_processor().create_batch(
            input_file_id=body["input_file_id"],
            endpoint=body.get("endpoint", "/v1/chat/completions"),
            completion_window=body.get("completion_window", "24h"),
            metadata=body.get("metadata"),
        )
        return web.json_response(info.to_dict())

    async def get_batch(self, request: web.Request) -> web.Response:
        try:
            info = await batch_service.get_batch_processor().retrieve_batch(
                request.match_info["batch_id"]
            )
        except KeyError:
            return web.json_response({"error": "batch not found"}, status=404)
        return web.json_response(info.to_dict())

    async def list_batches(self, request: web.Request) -> web.Response:
        infos = await batch_service.get_batch_processor().list_batches()
        return web.json_response(
            {"object": "list", "data": [i.to_dict() for i in infos]}
        )

    async def cancel_batch(self, request: web.Request) -> web.Response:
        try:
            info = await batch_service.get_batch_processor().cancel_batch(
                request.match_info["batch_id"]
            )
        except KeyError:
            return web.json_response({"error": "batch not found"}, status=404)
        return web.json_response(info.to_dict())

    async def sleep(self, request):
        return await route_sleep_wakeup_request(request, "/sleep")

    async def wake_up(self, request):
        return await route_sleep_wakeup_request(request, "/wake_up")

    async def is_sleeping(self, request):
        return await route_sleep_wakeup_request(request, "/is_sleeping")

    # -- app ----------------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application()
        r = app.router
        for ep in (
            "/v1/chat/completions", "/v1/completions", "/v1/embeddings",
            "/v1/rerank", "/v1/score", "/tokenize", "/detokenize",
        ):
            r.add_post(ep, self._proxy)
        r.add_get("/v1/models", self.models)
        r.add_get("/health", self.health)
        r.add_get("/metrics", self.metrics)
        if getattr(self.args, "enable_debug_endpoints", False):
            # unauthenticated debug surfaces — benchmark/debug runs only
            # (/v1/traces is read-only but exposes request ids, backends,
            # and per-request timings; /metrics/reset is state-mutating)
            r.add_get("/v1/traces", self.traces)
            r.add_post("/metrics/reset", self.metrics_reset)
        r.add_get("/engines", self.engines)
        r.add_get("/version", self.version)
        r.add_post("/v1/files", self.upload_file)
        r.add_get("/v1/files", self.list_files)
        r.add_get("/v1/files/{file_id}", self.get_file)
        r.add_get("/v1/files/{file_id}/content", self.get_file_content)
        r.add_post("/v1/batches", self.create_batch)
        r.add_get("/v1/batches", self.list_batches)
        r.add_get("/v1/batches/{batch_id}", self.get_batch)
        r.add_post("/v1/batches/{batch_id}/cancel", self.cancel_batch)
        r.add_post("/sleep", self.sleep)
        r.add_post("/wake_up", self.wake_up)
        r.add_get("/is_sleeping", self.is_sleeping)
        app.on_cleanup.append(self._cleanup)
        return app

    async def _cleanup(self, app) -> None:
        from production_stack_tpu.router.utils import cancel_task

        for t in self._bg:
            await cancel_task(t)
        # close every service that may have started a background task, so the
        # loop never shuts down with pending tasks ("Task was destroyed" noise)
        from production_stack_tpu.router import batch_service
        from production_stack_tpu.router.service_discovery import get_service_discovery

        for closable in (
            lambda: get_service_discovery(),
            lambda: get_engine_stats_scraper(),
            lambda: DynamicConfigWatcher.get(),
            lambda: batch_service.get_batch_processor(),
        ):
            try:
                svc = closable()
                if svc is not None:
                    await svc.close()
            except Exception:  # noqa: BLE001 - service may never have started
                pass
        await close_client_session()


async def serve(args):
    router = RouterApp(args)
    await router.initialize_all()
    app = router.build_app()
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, args.host, args.port)
    await site.start()
    logger.info("router listening on %s:%d (routing=%s, discovery=%s)",
                args.host, args.port, args.routing_logic, args.service_discovery)
    return router, runner


def main():
    import os

    from production_stack_tpu.utils.signals import wait_for_termination

    args = parse_args()
    set_ulimit()

    async def _run():
        router, runner = await serve(args)
        await wait_for_termination()
        # SIGTERM: flip /health to 503 so the LB/readiness pulls this pod,
        # give the fleet a beat to notice, then let AppRunner.cleanup drain
        # in-flight streaming proxies (its shutdown waits on live handlers).
        # PSTPU_DRAIN_TIMEOUT should sit inside the pod's
        # terminationGracePeriodSeconds (helm routerSpec).
        router.draining = True
        await asyncio.sleep(float(os.environ.get("PSTPU_DRAIN_NOTICE", "2")))
        try:
            await asyncio.wait_for(
                runner.cleanup(),
                float(os.environ.get("PSTPU_DRAIN_TIMEOUT", "60")),
            )
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass
        logger.info("router shut down cleanly")

    asyncio.run(_run())


if __name__ == "__main__":
    main()
