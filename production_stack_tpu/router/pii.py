"""PII detection middleware (experimental, behind --feature-gates PIIDetection=true).

Parity: src/vllm_router/experimental/pii/ in /root/reference —
check_pii_content middleware.py:43-154, RegexAnalyzer analyzers/regex.py:22
(Presidio analyzer is optional there and absent here).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

PATTERNS: dict[str, re.Pattern] = {
    "EMAIL": re.compile(r"[a-zA-Z0-9_.+-]+@[a-zA-Z0-9-]+\.[a-zA-Z0-9-.]+"),
    "PHONE": re.compile(r"\+?\d[\d\s().-]{7,}\d"),
    "SSN": re.compile(r"\b\d{3}-\d{2}-\d{4}\b"),
    "CREDIT_CARD": re.compile(r"\b(?:\d[ -]*?){13,16}\b"),
    "IP_ADDRESS": re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b"),
    "API_KEY": re.compile(r"\b(?:sk|pk|rk)-[A-Za-z0-9]{16,}\b"),
}


@dataclasses.dataclass
class PIIMatch:
    kind: str
    start: int
    end: int
    text: str


class RegexAnalyzer:
    def analyze(self, text: str) -> list[PIIMatch]:
        out = []
        for kind, pat in PATTERNS.items():
            for m in pat.finditer(text):
                out.append(PIIMatch(kind, m.start(), m.end(), m.group()))
        return out


def check_pii_content(text: str) -> list[PIIMatch]:
    return RegexAnalyzer().analyze(text)


def redact(text: str, matches: Optional[list[PIIMatch]] = None) -> str:
    matches = matches if matches is not None else check_pii_content(text)
    for m in sorted(matches, key=lambda m: -m.start):
        text = text[: m.start] + f"[{m.kind}]" + text[m.end :]
    return text
