"""PII detection middleware (experimental, behind --feature-gates PIIDetection=true).

Parity: src/vllm_router/experimental/pii/ in /root/reference —
check_pii_content middleware.py:43-154, RegexAnalyzer analyzers/regex.py:22,
PresidioAnalyzer analyzers/presidio.py:45. Presidio is optional-import in
the reference and here alike (pyproject extra ``pii``): ``make_analyzer``
returns the Presidio tier when the package is installed and the regex
analyzer otherwise.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

PATTERNS: dict[str, re.Pattern] = {
    "EMAIL": re.compile(r"[a-zA-Z0-9_.+-]+@[a-zA-Z0-9-]+\.[a-zA-Z0-9-.]+"),
    "PHONE": re.compile(r"\+?\d[\d\s().-]{7,}\d"),
    "SSN": re.compile(r"\b\d{3}-\d{2}-\d{4}\b"),
    "CREDIT_CARD": re.compile(r"\b(?:\d[ -]*?){13,16}\b"),
    "IP_ADDRESS": re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b"),
    "API_KEY": re.compile(r"\b(?:sk|pk|rk)-[A-Za-z0-9]{16,}\b"),
}


@dataclasses.dataclass
class PIIMatch:
    kind: str
    start: int
    end: int
    text: str


class RegexAnalyzer:
    def analyze(self, text: str) -> list[PIIMatch]:
        out = []
        for kind, pat in PATTERNS.items():
            for m in pat.finditer(text):
                out.append(PIIMatch(kind, m.start(), m.end(), m.group()))
        return out


class PresidioAnalyzer:
    """Microsoft Presidio NER tier (reference: analyzers/presidio.py:45).
    Activates when ``presidio_analyzer`` is installed; inject ``engine`` to
    test the adapter without it."""

    def __init__(self, engine=None, language: str = "en"):
        if engine is None:
            from presidio_analyzer import AnalyzerEngine  # optional dep

            engine = AnalyzerEngine()
        self._engine = engine
        self.language = language

    def analyze(self, text: str) -> list[PIIMatch]:
        results = self._engine.analyze(text=text, language=self.language)
        return [
            PIIMatch(r.entity_type, r.start, r.end, text[r.start : r.end])
            for r in results
        ]


_analyzer = None


def make_analyzer(kind: str = "auto"):
    """regex | presidio | auto (presidio when installed, else regex)."""
    if kind in ("auto", "presidio"):
        try:
            a = PresidioAnalyzer()
            logger.info("PII detection: Presidio analyzer")
            return a
        except Exception as e:  # noqa: BLE001 - package absent
            if kind == "presidio":
                raise RuntimeError(
                    f"--pii-analyzer presidio requires presidio_analyzer: {e}"
                ) from e
    return RegexAnalyzer()


def check_pii_content(text: str, analyzer=None) -> list[PIIMatch]:
    global _analyzer
    if analyzer is None:
        if _analyzer is None:
            _analyzer = make_analyzer()
        analyzer = _analyzer
    return analyzer.analyze(text)


def redact(
    text: str, matches: Optional[list[PIIMatch]] = None, analyzer=None
) -> str:
    matches = matches if matches is not None else check_pii_content(text, analyzer)
    for m in sorted(matches, key=lambda m: -m.start):
        text = text[: m.start] + f"[{m.kind}]" + text[m.end :]
    return text
