"""Router utilities: singletons, model typing, health probes.

Parity: src/vllm_router/utils.py in /root/reference (SingletonMeta :16-45,
ModelType health payloads :48-81, is_model_healthy :160-175).
"""

from __future__ import annotations

import asyncio
import enum
import resource
from typing import Optional

import aiohttp

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


async def cancel_task(task: Optional["asyncio.Task"]) -> None:
    """Cancel a background task and wait for it to actually finish, so loop
    shutdown never destroys a still-pending task."""
    if task is None:
        return
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        if not task.cancelled():
            # the CancelledError came from OUR caller being cancelled
            # mid-shutdown, not from the awaited task — propagate it
            raise
    except Exception as e:  # noqa: BLE001
        logger.warning("background task %r died: %s: %s",
                       task.get_name(), type(e).__name__, e)


class SingletonMeta(type):
    _instances: dict = {}

    def __call__(cls, *args, **kwargs):
        if cls not in cls._instances:
            cls._instances[cls] = super().__call__(*args, **kwargs)
        return cls._instances[cls]

    @classmethod
    def _reset(mcs, cls) -> None:
        mcs._instances.pop(cls, None)


class ModelType(enum.Enum):
    chat = "/v1/chat/completions"
    completion = "/v1/completions"
    embeddings = "/v1/embeddings"
    rerank = "/v1/rerank"
    score = "/v1/score"

    @staticmethod
    def get_test_payload(model_type: str) -> dict:
        return {
            "chat": {"messages": [{"role": "user", "content": "Hi"}], "max_tokens": 2},
            "completion": {"prompt": "Hi", "max_tokens": 2},
            "embeddings": {"input": "Hi"},
            "rerank": {"query": "Hi", "documents": ["a"]},
            "score": {"text_1": "a", "text_2": "b"},
        }[model_type]

    @staticmethod
    def get_all_fields() -> list[str]:
        return [m.name for m in ModelType]


async def is_model_healthy(url: str, model: str, model_type: str, timeout: float = 10.0) -> bool:
    """Send a real dummy request of the right type (parity: utils.py:160-175)."""
    endpoint = ModelType[model_type].value
    payload = {"model": model, **ModelType.get_test_payload(model_type)}
    try:
        from production_stack_tpu.router.request_service import get_client_session

        session = await get_client_session()
        async with session.post(
            f"{url}{endpoint}", json=payload,
            timeout=aiohttp.ClientTimeout(total=timeout),
        ) as resp:
            return resp.status == 200
    except Exception:
        return False


def set_ulimit(target: int = 65535) -> None:
    try:
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < target:
            resource.setrlimit(resource.RLIMIT_NOFILE, (min(target, hard), hard))
    except Exception as e:
        logger.warning("could not raise ulimit: %s", e)


def parse_comma_separated(value: Optional[str]) -> list[str]:
    return [v.strip() for v in value.split(",") if v.strip()] if value else []


def parse_static_urls(static_backends: str) -> list[str]:
    return parse_comma_separated(static_backends)


def parse_static_model_names(static_models: str) -> list[str]:
    return parse_comma_separated(static_models)
