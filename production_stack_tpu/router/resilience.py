"""Failure-domain layer for the router data plane.

Three cooperating mechanisms (docs/failure-handling.md):

- **RetryPolicy** — connect-stage and pre-first-byte proxy failures are
  retried with capped exponential backoff + full jitter against the routing
  logic's next-choice endpoint, bounded by an attempt budget and a
  per-request deadline. Mid-stream failures are never retried (tokens have
  already reached the client); they surface as a terminal SSE error event.
- **Deadlines** — a TTFT deadline bounds connect→first-byte, an inter-chunk
  stall timeout bounds each gap between streamed chunks. Both abort the
  backend request AND fire a best-effort ``POST /abort`` on the engine so
  scheduler slots and KV pages are reclaimed instead of leaking behind a
  dead TCP connection.
- **CircuitBreaker** — every proxy outcome feeds a per-backend breaker
  (closed → open after N consecutive failures → half-open probe after a
  cooldown → closed again on success). Routing consults the breakers in
  addition to the optional active health-check loop, so static-discovery
  deployments react to failures without probe traffic. Breaker filtering is
  fail-static: when EVERY candidate's breaker is open the original list is
  returned unchanged — a fully-tripped fleet must degrade to "try anyway",
  never to a synthesized 503.
- **SaturationRegistry** — backend 429 + Retry-After responses (engine load
  shedding, docs/failure-handling.md overload section) mark the backend
  saturated for the advertised window. Shed-aware failover moves the request
  to the next engine immediately WITHOUT feeding the breaker (an overloaded
  engine is healthy), and routing stops offering saturated backends new
  non-sticky traffic until the window elapses.

All state is mutated from the router's single event loop; plain ints are
safe counters here. Rendered into /metrics by ``render_resilience_metrics``
(vllm_router:retries_total, vllm_router:failovers_total,
vllm_router:deadline_aborts_total, vllm_router:circuit_state,
vllm_router:circuit_open_events_total).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

# breaker states, also the circuit_state gauge encoding
CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half_open", OPEN: "open"}


@dataclasses.dataclass
class RetryPolicy:
    """Retry/deadline knobs (parser --retry-* / --deadline-* flags).

    ``deadline_request`` bounds the ATTEMPT phase (connect + retries up to
    the first streamed byte), not the stream itself — a 10-minute legitimate
    decode must not be killed by a retry budget. 0 disables a deadline.
    """

    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_max: float = 2.0
    deadline_request: float = 0.0
    deadline_ttft: float = 0.0
    deadline_inter_chunk: float = 0.0

    def backoff(self, attempt: int) -> float:
        """Capped exponential backoff with full jitter (attempt is 1-based:
        the delay before attempt N+1 after attempt N failed)."""
        cap = min(self.backoff_max, self.backoff_base * (2 ** max(0, attempt - 1)))
        return random.uniform(0, cap)

    def remaining(self, t_start: float, now: Optional[float] = None) -> Optional[float]:
        """Seconds left in the attempt-phase deadline, or None if unbounded."""
        if self.deadline_request <= 0:
            return None
        return self.deadline_request - ((now or time.monotonic()) - t_start)


class CircuitBreaker:
    """Passive per-backend breaker.

    closed: traffic flows; ``failure_threshold`` consecutive failures open it.
    open: traffic is filtered out until ``cooldown`` elapses.
    half-open: admits traffic; the first recorded outcome decides — success
    closes, failure re-opens (and restarts the cooldown). No active probes:
    the next real request IS the probe, which is what makes this work for
    static-discovery deployments with no health loop.
    """

    def __init__(self, failure_threshold: int = 5, cooldown: float = 30.0):
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self.open_events = 0

    def allow(self, now: Optional[float] = None) -> bool:
        if self.failure_threshold <= 0:  # breaker disabled
            return True
        if self.state == OPEN:
            if now is None:
                now = time.monotonic()
            if now - self.opened_at >= self.cooldown:
                self.state = HALF_OPEN
                return True
            return False
        return True

    def record_success(self) -> None:
        if self.state != CLOSED:
            logger.info("circuit breaker closing (probe succeeded)")
        self.state = CLOSED
        self.consecutive_failures = 0

    def record_probe_success(self) -> None:
        """Out-of-band evidence (active health loop): an OPEN breaker skips
        the rest of its cooldown and goes half-open, but probe traffic never
        ERASES data-plane failure evidence — a backend can pass a 1-token
        dummy probe while 500ing or stalling real requests, and only a real
        request outcome may close the breaker."""
        if self.state == OPEN:
            self.state = HALF_OPEN

    def record_failure(self, now: Optional[float] = None) -> None:
        if self.failure_threshold <= 0:
            return
        self.consecutive_failures += 1
        if self.state == HALF_OPEN or (
            self.state == CLOSED
            and self.consecutive_failures >= self.failure_threshold
        ):
            self.state = OPEN
            self.opened_at = time.monotonic() if now is None else now
            self.open_events += 1

    def peek_state(self, now: Optional[float] = None) -> int:
        """The state the NEXT allow() would see, WITHOUT mutating: a metrics
        scrape must not flip open→half-open itself — that would let scrape
        frequency influence when a straggler failure restarts the cooldown."""
        if self.state == OPEN:
            if now is None:
                now = time.monotonic()
            if now - self.opened_at >= self.cooldown:
                return HALF_OPEN
        return self.state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self.state]


class BreakerRegistry:
    """URL-keyed breakers + the fail-static endpoint filter."""

    def __init__(self, failure_threshold: int = 5, cooldown: float = 30.0):
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self._breakers: dict[str, CircuitBreaker] = {}

    def breaker(self, url: str) -> CircuitBreaker:
        b = self._breakers.get(url)
        if b is None:
            b = self._breakers[url] = CircuitBreaker(
                self.failure_threshold, self.cooldown
            )
        return b

    def record_success(self, url: str) -> None:
        self.breaker(url).record_success()

    def record_probe_success(self, url: str) -> None:
        self.breaker(url).record_probe_success()

    def record_failure(self, url: str) -> None:
        b = self.breaker(url)
        was = b.state
        b.record_failure()
        if b.state == OPEN and was != OPEN:
            logger.warning(
                "circuit breaker OPEN for %s after %d consecutive failures",
                url, b.consecutive_failures,
            )

    def allows(self, url: str) -> bool:
        return self.breaker(url).allow()

    def filter_endpoints(self, endpoints: list, *, fail_static: bool = True) -> list:
        """Drop endpoints whose breaker is open. With ``fail_static`` (the
        routing path), an all-open candidate set is returned unchanged so the
        router degrades to trying a tripped backend rather than 503ing; the
        failover path passes False because it has a better option — giving
        up the retry and surfacing the original error."""
        allowed = [ep for ep in endpoints if self.allows(ep.url)]
        if not allowed and fail_static:
            return list(endpoints)
        return allowed

    def open_urls(self) -> list[str]:
        return sorted(
            url for url, b in self._breakers.items() if b.state == OPEN
        )

    def forget(self, url: str) -> None:
        """Drop a backend's breaker (pod deleted): a replacement pod reusing
        the address must start closed, not inherit the corpse's open state."""
        self._breakers.pop(url, None)

    def states(self) -> dict[str, CircuitBreaker]:
        return dict(self._breakers)


class SessionPinRegistry:
    """Session re-pins after a live migration (docs/migration.md).

    A SessionRouter maps a session key to its backend through a consistent
    hash ring — deterministic, so a stream migrated off its hashed home
    would bounce straight back on the session's next request and thrash.
    A pin is an explicit override: "this session now lives on <url>",
    written by the router's stream-splice path when it hands a migrated
    stream over, consulted by SessionRouter before the ring, expired by TTL
    (a session that goes quiet long enough re-homes via the ring, which is
    also how pins converge back after a scale event)."""

    TTL_S = 1800.0

    def __init__(self):
        self._pins: dict[str, tuple[str, float]] = {}  # sid -> (url, expiry)

    def pin(self, session_id: str, url: str, ttl: Optional[float] = None) -> None:
        self._pins[session_id] = (
            url, time.monotonic() + (ttl if ttl is not None else self.TTL_S)
        )

    def lookup(self, session_id: str, now: Optional[float] = None) -> Optional[str]:
        ent = self._pins.get(session_id)
        if ent is None:
            return None
        url, expiry = ent
        if (now or time.monotonic()) >= expiry:
            del self._pins[session_id]
            return None
        return url

    def forget_backend(self, url: str) -> None:
        """Backend gone (pod deleted / drained away): its pins must not keep
        steering sessions at a corpse."""
        for sid in [s for s, (u, _) in self._pins.items() if u == url]:
            del self._pins[sid]

    def clear(self) -> None:
        self._pins.clear()

    def __len__(self) -> int:
        return len(self._pins)


class SaturationRegistry:
    """Per-backend load-shed state (overload survival).

    A backend answering 429 + Retry-After is SHEDDING, not failing: it is
    healthy, it just has no capacity right now. The registry remembers the
    advertised Retry-After window so routing stops offering the backend new
    non-sticky traffic until the window elapses — a scrape-interval-fast
    signal (the engine-stats gauge lags by up to a scrape period). Distinct
    from the circuit breaker by design: sheds never feed the breaker, so an
    overloaded-but-alive fleet can't trip itself into fail-static mode.
    """

    def __init__(self):
        self._until: dict[str, float] = {}  # url -> monotonic expiry
        # every backend that EVER shed, for 1->0 gauge transitions: a series
        # that vanishes instead of flipping to 0 leaves Prometheus showing a
        # stale 1 until the staleness interval, and `== 0` alerts never fire
        self._seen: set[str] = set()

    # shed-window clamp (defense in depth with request_service's Retry-After
    # parser): one 429 must never exclude a backend for longer than this
    MAX_WINDOW_S = 60.0

    def mark(self, url: str, retry_after_s: float) -> None:
        window = min(self.MAX_WINDOW_S, max(0.5, retry_after_s))
        self._until[url] = time.monotonic() + window
        self._seen.add(url)

    def is_saturated(self, url: str, now: Optional[float] = None) -> bool:
        until = self._until.get(url)
        if until is None:
            return False
        if (now or time.monotonic()) >= until:
            del self._until[url]  # window elapsed: eligible again
            return False
        return True

    def saturated_urls(self) -> list[str]:
        now = time.monotonic()
        return sorted(u for u in list(self._until) if self.is_saturated(u, now))

    def seen_urls(self) -> list[str]:
        return sorted(self._seen)

    def forget(self, url: str) -> None:
        """Backend gone (pod deleted): drop its window AND its gauge row."""
        self._until.pop(url, None)
        self._seen.discard(url)

    def clear(self) -> None:
        self._until.clear()
        self._seen.clear()


# -- counters (event-loop-only mutation; rendered by app.py /metrics) --------

retries_total = 0
failovers_total = 0
sheds_total = 0  # backend 429s observed (shed-aware failover, not failures)
deadline_aborts_total: dict[str, int] = {"ttft": 0, "inter_chunk": 0, "request": 0}
# live-migration stream handoffs the proxy spliced (each is a session re-pin
# of the in-flight stream; SessionRouter pins are registered alongside)
session_repins_total = 0
# handoffs that failed after the source committed (the client got the SSE
# error-event contract instead of a silent truncation)
migration_splice_failures_total = 0
# per-SLO-class request tagging (docs/failure-handling.md priority classes):
# closed label set, zero rows always rendered so dashboards see the split
# from the first scrape
requests_by_class_total: dict[str, int] = {"interactive": 0, "batch": 0}
# batch requests steered away from at least one backend whose interactive
# SLO attainment was degraded (RoutingInterface.class_filtered shrank the
# candidate set) — a flat line under overload means the avoidance filter
# never engaged
batch_deprioritized_routes_total = 0


def count_retry() -> None:
    global retries_total
    retries_total += 1


def count_failover() -> None:
    global failovers_total
    failovers_total += 1


def count_shed() -> None:
    global sheds_total
    sheds_total += 1


def count_deadline_abort(kind: str) -> None:
    deadline_aborts_total[kind] = deadline_aborts_total.get(kind, 0) + 1


def count_request_class(priority: str) -> None:
    key = priority if priority in requests_by_class_total else "interactive"
    requests_by_class_total[key] += 1


def count_batch_deprioritized() -> None:
    global batch_deprioritized_routes_total
    batch_deprioritized_routes_total += 1


def count_session_repin() -> None:
    global session_repins_total
    session_repins_total += 1


def count_migration_splice_failure() -> None:
    global migration_splice_failures_total
    migration_splice_failures_total += 1


def reset_counters() -> None:
    """Test/bench support (mirrors reset_hop_samples): live Prometheus
    counters never reset outside a process restart."""
    global retries_total, failovers_total, sheds_total
    global session_repins_total, migration_splice_failures_total
    global batch_deprioritized_routes_total
    retries_total = 0
    failovers_total = 0
    sheds_total = 0
    session_repins_total = 0
    migration_splice_failures_total = 0
    batch_deprioritized_routes_total = 0
    for k in list(deadline_aborts_total):
        deadline_aborts_total[k] = 0
    for k in list(requests_by_class_total):
        requests_by_class_total[k] = 0


def render_resilience_metrics() -> list[str]:
    """Prometheus exposition lines for the failure-domain layer."""
    lines = [
        "# TYPE vllm_router:retries_total counter",
        f"vllm_router:retries_total {retries_total}",
        "# TYPE vllm_router:failovers_total counter",
        f"vllm_router:failovers_total {failovers_total}",
        "# TYPE vllm_router:sheds_total counter",
        f"vllm_router:sheds_total {sheds_total}",
        "# TYPE vllm_router:session_repins_total counter",
        f"vllm_router:session_repins_total {session_repins_total}",
        "# TYPE vllm_router:migration_splice_failures_total counter",
        f"vllm_router:migration_splice_failures_total "
        f"{migration_splice_failures_total}",
        "# TYPE vllm_router:batch_deprioritized_routes_total counter",
        f"vllm_router:batch_deprioritized_routes_total "
        f"{batch_deprioritized_routes_total}",
        "# TYPE vllm_router:deadline_aborts_total counter",
    ]
    for kind, n in sorted(deadline_aborts_total.items()):
        lines.append(f'vllm_router:deadline_aborts_total{{kind="{kind}"}} {n}')
    lines.append("# TYPE vllm_router:requests_by_class_total counter")
    for pri, n in sorted(requests_by_class_total.items()):
        lines.append(
            f'vllm_router:requests_by_class_total{{priority="{pri}"}} {n}'
        )
    reg = get_breaker_registry()
    states = reg.states()
    if states:
        lines.append("# TYPE vllm_router:circuit_state gauge")
        for url, b in sorted(states.items()):
            # read-only view of what the NEXT routing decision would see
            # (an elapsed cooldown shows half-open without mutating state)
            lines.append(
                f'vllm_router:circuit_state{{backend="{url}"}} {b.peek_state()}'
            )
        lines.append("# TYPE vllm_router:circuit_open_events_total counter")
        for url, b in sorted(states.items()):
            lines.append(
                f'vllm_router:circuit_open_events_total{{backend="{url}"}} {b.open_events}'
            )
    sat_reg = get_saturation_registry()
    seen = sat_reg.seen_urls()
    if seen:
        active = set(sat_reg.saturated_urls())
        lines.append("# TYPE vllm_router:backend_saturated gauge")
        for url in seen:  # 0 rows included: the gauge flips, never vanishes
            lines.append(
                f'vllm_router:backend_saturated{{backend="{url}"}} '
                f"{int(url in active)}"
            )
    return lines


# -- singletons --------------------------------------------------------------

_policy: Optional[RetryPolicy] = None
_registry: Optional[BreakerRegistry] = None
_saturation: Optional[SaturationRegistry] = None
_session_pins: Optional[SessionPinRegistry] = None


def get_saturation_registry() -> SaturationRegistry:
    global _saturation
    if _saturation is None:
        _saturation = SaturationRegistry()
    return _saturation


def get_session_pins() -> SessionPinRegistry:
    global _session_pins
    if _session_pins is None:
        _session_pins = SessionPinRegistry()
    return _session_pins


def initialize_resilience(
    *,
    retry_max_attempts: int = 3,
    retry_backoff_base: float = 0.05,
    retry_backoff_max: float = 2.0,
    deadline_request: float = 0.0,
    deadline_ttft: float = 0.0,
    deadline_inter_chunk: float = 0.0,
    breaker_failure_threshold: int = 5,
    breaker_cooldown: float = 30.0,
) -> None:
    global _policy, _registry
    get_saturation_registry().clear()  # reconfigure: no stale shed windows
    get_session_pins().clear()  # ...and no stale migration pins
    _policy = RetryPolicy(
        max_attempts=retry_max_attempts,
        backoff_base=retry_backoff_base,
        backoff_max=retry_backoff_max,
        deadline_request=deadline_request,
        deadline_ttft=deadline_ttft,
        deadline_inter_chunk=deadline_inter_chunk,
    )
    _registry = BreakerRegistry(breaker_failure_threshold, breaker_cooldown)
    logger.info(
        "resilience layer: attempts=%d backoff=%.3fs..%.1fs deadlines "
        "request=%.1fs ttft=%.1fs inter_chunk=%.1fs breaker threshold=%d "
        "cooldown=%.1fs",
        retry_max_attempts, retry_backoff_base, retry_backoff_max,
        deadline_request, deadline_ttft, deadline_inter_chunk,
        breaker_failure_threshold, breaker_cooldown,
    )


def get_retry_policy() -> RetryPolicy:
    global _policy
    if _policy is None:  # unit tests / embedded use: defaults apply
        _policy = RetryPolicy()
    return _policy


def get_breaker_registry() -> BreakerRegistry:
    global _registry
    if _registry is None:
        _registry = BreakerRegistry()
    return _registry
