"""User-supplied pre/post request hooks.

Parity: src/vllm_router/services/callbacks_service/custom_callbacks.py:20-55 in
/root/reference — a `--callbacks module.py:instance` file is loaded at startup;
`pre_request` may short-circuit with a response, `post_request` observes the
full response body in the background.
"""

from __future__ import annotations

import importlib.util
import sys
from typing import Any, Optional


class CustomCallbackHandler:
    def pre_request(self, request: Any, request_body: bytes, request_json: dict):
        """Return None to continue, or a (status, dict) tuple to short-circuit."""
        return None

    def post_request(self, request: Any, response_body: bytes) -> None:
        return None


_handler: Optional[CustomCallbackHandler] = None


def load_callbacks(spec: str) -> CustomCallbackHandler:
    """`/path/to/file.py:attribute` -> the attribute (an instance)."""
    global _handler
    path, _, attr = spec.partition(":")
    module_spec = importlib.util.spec_from_file_location("_router_callbacks", path)
    module = importlib.util.module_from_spec(module_spec)
    sys.modules["_router_callbacks"] = module
    module_spec.loader.exec_module(module)
    _handler = getattr(module, attr or "handler")
    return _handler


def get_callbacks() -> Optional[CustomCallbackHandler]:
    return _handler
