"""Startup host<->device link-bandwidth probe.

``kv_offload_max_io_pages`` — the per-operation page budget for KV offload
spills and restores — used to be a hand-tuned constant: 0 (unbounded) on
PCIe-attached hosts, ~8 on the network-attached axon tunnel. The right value
is a pure function of the host<->device link bandwidth, so the engine now
measures it once at startup (a few round trips of an ~8 MB buffer) and
derives the cap; the measured bandwidth and chosen cap are exported on
/metrics so operators can see what the probe decided. An explicit
``--kv-offload-max-io-pages >= 0`` skips the probe entirely (manual override
honored).
"""

from __future__ import annotations

import time
from typing import Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

# links at or above this are "PCIe-class": restore always beats recompute,
# so the I/O budget stays unbounded
FAST_LINK_BYTES_PER_S = 1.0e9
# worst-case engine-loop stall one capped offload operation may cost
STALL_BUDGET_S = 0.25


def probe_link_bandwidth(
    nbytes: int = 8 << 20, trials: int = 3
) -> Optional[float]:
    """Measured host->device->host round-trip bandwidth in bytes/second
    (best of ``trials``), or None when the device runtime refuses the probe.
    Uses the same transfer primitives the offload connector pays for
    (device_put upload, np.asarray fetch), so the number reflects what a
    spill/restore batch would actually see.

    Staged so a SLOW link never pays a big probe: a ~1 MB pilot decides
    first — on a clearly-slow link (the very case the cap exists for) the
    pilot's estimate already settles the cap decision and the full-size
    trials are skipped, keeping the startup stall ~milliseconds instead of
    seconds; only fast links (where the transfer is cheap anyway) run the
    larger trials for an accurate number."""
    try:
        import jax
        import numpy as np

        def round_trip(buf) -> float:
            t0 = time.perf_counter()
            dev = jax.device_put(buf)
            dev.block_until_ready()
            np.asarray(dev)  # device -> host leg
            dt = time.perf_counter() - t0
            return 2 * buf.nbytes / dt if dt > 0 else 0.0

        pilot_bytes = min(nbytes, 1 << 20)
        pilot = np.zeros(pilot_bytes, np.uint8)
        warm = jax.device_put(pilot)
        warm.block_until_ready()  # absorb transfer-path setup
        np.asarray(warm)
        pilot_bw = max(round_trip(pilot), round_trip(pilot))
        if not pilot_bw:
            return None
        if pilot_bw < FAST_LINK_BYTES_PER_S / 8:
            return pilot_bw  # unambiguously slow: decision already made
        host = np.zeros(nbytes, np.uint8)
        best = max(round_trip(host) for _ in range(trials))
        return max(best, pilot_bw) or None
    except Exception as e:  # noqa: BLE001 - probe must never kill startup
        logger.warning("link-bandwidth probe failed (%s); cap stays unbounded", e)
        return None


def derive_max_io_pages(
    bandwidth_bytes_per_s: Optional[float],
    page_bytes: int,
    *,
    stall_budget_s: float = STALL_BUDGET_S,
    fast_link_bytes_per_s: float = FAST_LINK_BYTES_PER_S,
) -> int:
    """Offload I/O page cap for a measured link bandwidth.

    - unknown bandwidth (failed probe) or PCIe-class links -> 0 (unbounded);
    - slow links -> the page count one ``stall_budget_s`` stall can move, at
      least 1 so chain heads stay restorable.
    """
    if not bandwidth_bytes_per_s or bandwidth_bytes_per_s >= fast_link_bytes_per_s:
        return 0
    return max(1, int(bandwidth_bytes_per_s * stall_budget_s / max(page_bytes, 1)))
