"""OpenAI-compatible HTTP server for the TPU engine (aiohttp).

Implements the serving-engine contract the reference stack expects of vLLM
(SURVEY.md §1 L4): OpenAI API, Prometheus `/metrics` with `vllm:*`-compatible
metric names (so the reference's router scraper, Grafana dashboards, and
prometheus-adapter autoscaling rules work unchanged — stats/engine_stats.py:63-76
in /root/reference), `/health`, `/v1/models`, `/tokenize`, `/detokenize`, and
the sleep/wake endpoints used for pod hibernation
(service_discovery.py:383-408 in /root/reference).
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import inspect
import json
import time
import uuid
from typing import Optional

from aiohttp import web

from production_stack_tpu import __version__
from production_stack_tpu.engine.config import EngineConfig, add_engine_args, config_from_args
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.engine.scheduler import SamplingParams
from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

# Per-request TTFT hop samples for streaming requests, in ms:
# (accept->engine-submit, submit->first engine output, first output->first
# SSE write). /metrics exposes p50/p99 per hop; together with the router's
# hop gauges this attributes stack tail latency to a stage.
_ttft_hops: collections.deque = collections.deque(maxlen=2048)

# Cumulative distributions backing the dashboard's TTFT / latency heatmap
# panels (reference vllm-dashboard.json:34-1312); vLLM-compatible names and
# bucket boundaries so those panel queries work unchanged.
from production_stack_tpu.utils.metrics import (  # noqa: E402
    LATENCY_BUCKETS,
    TTFT_BUCKETS,
    Histogram,
)

_ttft_hist = Histogram(
    "vllm:time_to_first_token_seconds", TTFT_BUCKETS,
    "Time to first token distribution",
)
_latency_hist = Histogram(
    "vllm:e2e_request_latency_seconds", LATENCY_BUCKETS,
    "End-to-end request latency distribution",
)


def _ttft_hop_quantiles() -> dict:
    if not _ttft_hops:
        return {}
    names = ("accept_to_submit", "submit_to_first_token", "first_token_to_write")
    out = {}
    for name, vals in zip(names, zip(*_ttft_hops)):
        s = sorted(vals)
        out[name] = {
            "p50": s[len(s) // 2],
            "p99": s[min(len(s) - 1, int(len(s) * 0.99))],
        }
    return out


async def _tag_stream(i, gen):
    async for out in gen:
        yield i, out


async def _merge_streams(gens):
    """Merge n RequestOutput streams into (choice_index, output) tuples,
    preserving per-stream order."""
    q: asyncio.Queue = asyncio.Queue()

    async def pump(i, g):
        try:
            async for out in g:
                await q.put((i, out))
        except Exception as e:  # surface stream errors to the consumer
            await q.put((i, e))
        finally:
            await q.put((i, None))

    tasks = [asyncio.ensure_future(pump(i, g)) for i, g in enumerate(gens)]
    try:
        open_streams = len(gens)
        while open_streams:
            i, out = await q.get()
            if out is None:
                open_streams -= 1
                continue
            if isinstance(out, Exception):
                raise out
            yield i, out
    finally:
        for t in tasks:
            t.cancel()


def _chat_lp_content(tok, token_ids, entries):
    """OpenAI chat logprobs format: choices[].logprobs.content[]."""
    content = []
    for tid, e in zip(token_ids, entries):
        s = tok.decode([tid])
        content.append({
            "token": s,
            "logprob": e["logprob"],
            "bytes": list(s.encode("utf-8", errors="replace")),
            "top_logprobs": [
                {
                    "token": tok.decode([i]),
                    "logprob": lp,
                    "bytes": list(tok.decode([i]).encode("utf-8", errors="replace")),
                }
                for i, lp in zip(e["top_ids"], e["top_logprobs"])
            ],
        })
    return content


def _completion_lp(tok, token_ids, entries, offset0):
    """OpenAI completions logprobs format; returns (dict, next_offset)."""
    toks, tlps, tops, offs = [], [], [], []
    off = offset0
    for tid, e in zip(token_ids, entries):
        s = tok.decode([tid])
        toks.append(s)
        tlps.append(e["logprob"])
        top: dict = {}
        for i, lp in zip(e["top_ids"], e["top_logprobs"]):
            # distinct ids can decode to the same string (byte fragments);
            # entries arrive best-first, so keep the first (highest) lp
            top.setdefault(tok.decode([i]), lp)
        tops.append(top)
        offs.append(off)
        off += len(s)
    return (
        {"tokens": toks, "token_logprobs": tlps, "top_logprobs": tops,
         "text_offset": offs},
        off,
    )


def _sampling_params(
    body: dict, default_max: int = 256, vocab_size: "Optional[int]" = None
) -> SamplingParams:
    stop = body.get("stop") or []
    if isinstance(stop, str):
        stop = [stop]
    return SamplingParams(
        max_tokens=int(body.get("max_tokens") or body.get("max_completion_tokens") or default_max),
        temperature=float(body.get("temperature", 1.0)),
        top_k=int(body.get("top_k", 0)),
        top_p=float(body.get("top_p", 1.0)),
        stop=list(stop),
        ignore_eos=bool(body.get("ignore_eos", False)),
        min_tokens=int(body.get("min_tokens", 0)),
        seed=body.get("seed"),
        presence_penalty=float(body.get("presence_penalty", 0.0)),
        frequency_penalty=float(body.get("frequency_penalty", 0.0)),
        repetition_penalty=float(body.get("repetition_penalty", 1.0)),
        logit_bias=_parse_logit_bias(body.get("logit_bias"), vocab_size),
    )


def _parse_logit_bias(raw, vocab_size: "Optional[int]" = None) -> "Optional[dict]":
    """OpenAI logit_bias: {"<token_id>": bias in [-100, 100]}, <= 300 keys."""
    if not raw:
        return None
    if not isinstance(raw, dict) or len(raw) > 300:
        raise ValueError("logit_bias must be a dict of at most 300 entries")
    out = {}
    for k, v in raw.items():
        try:
            tid, bv = int(k), float(v)
        except (TypeError, ValueError):
            raise ValueError(f"invalid logit_bias entry {k!r}: {v!r}") from None
        if tid < 0:
            raise ValueError(f"logit_bias token id {tid} is negative")
        if vocab_size is not None and tid >= vocab_size:
            # OpenAI rejects out-of-vocab keys with a 400; silently dropping
            # them on device (scatter mode='drop') would hide client bugs
            raise ValueError(
                f"logit_bias token id {tid} out of range for vocab size {vocab_size}"
            )
        if not -100.0 <= bv <= 100.0:
            raise ValueError(f"logit_bias value {bv} outside [-100, 100]")
        out[tid] = bv
    return out


def _shed_response(retry_after_s: float, message: str) -> web.Response:
    """Load-shed contract (docs/failure-handling.md): an overloaded engine
    answers 429 with a Retry-After hint instead of queueing the request into
    unbounded TTFT. The shed-aware router treats this as an immediate
    failover signal that must NOT trip the circuit breaker."""
    retry = max(1, int(-(-retry_after_s // 1)))  # ceil, floor 1 s
    return web.json_response(
        {
            "error": {
                "message": message,
                "type": "overloaded_error",
                "code": 429,
            }
        },
        status=429,
        headers={"Retry-After": str(retry)},
    )


def _request_priority(headers, body) -> str:
    """Per-request SLO class (docs/failure-handling.md priority classes):
    the X-Priority header wins, then the body's "priority" field; anything
    outside the closed {interactive, batch} set degrades to interactive so
    the label cardinality stays bounded."""
    p = headers.get("X-Priority") or (
        body.get("priority") if isinstance(body, dict) else None
    )
    p = str(p).strip().lower() if p else "interactive"
    return p if p in ("interactive", "batch") else "interactive"


def _usage(out) -> dict:
    return {
        "prompt_tokens": out.prompt_tokens,
        "completion_tokens": out.completion_tokens,
        "total_tokens": out.prompt_tokens + out.completion_tokens,
        "prompt_tokens_details": {"cached_tokens": out.cached_tokens},
    }


class EngineServer:
    def _vocab_size(self) -> "Optional[int]":
        """Model vocab size for request validation, when the engine knows it
        (fake/test engines may not carry a model config)."""
        model_cfg = getattr(self.engine, "model_cfg", None)
        return getattr(model_cfg, "vocab_size", None)

    def __init__(self, cfg: EngineConfig, engine: Optional[LLMEngine] = None):
        self.cfg = cfg
        self.engine = engine or LLMEngine(cfg)
        try:
            gen_params = inspect.signature(self.engine.generate).parameters
            self._engine_accepts_trace = "trace" in gen_params
            self._engine_accepts_shed_exempt = "shed_exempt" in gen_params
            self._engine_accepts_priority = "priority" in gen_params
        except (TypeError, ValueError):
            self._engine_accepts_trace = False
            self._engine_accepts_shed_exempt = False
            self._engine_accepts_priority = False
        try:
            sat = getattr(self.engine, "saturated", None)
            self._saturated_accepts_priority = sat is not None and (
                "priority" in inspect.signature(sat).parameters
            )
        except (TypeError, ValueError):
            self._saturated_accepts_priority = False
        self.start_time = time.time()
        # device telemetry sampler (engine/devicemon.py): HBM per device,
        # KV pool vs headroom, compile activity, step duty cycle — rendered
        # into /metrics on scrape (duck-typed engines degrade gracefully)
        from production_stack_tpu.engine.devicemon import DeviceMonitor

        self.devmon = DeviceMonitor(self.engine)
        # graceful drain (SIGTERM): /health flips to 503 so readiness
        # probes / router health checks pull the pod from rotation, new
        # generation requests are refused, and in-flight ones finish
        self.draining = False
        # request-id -> (engine sequence ids, registered-at, streaming,
        # presentation meta), for router-initiated aborts (POST /abort) and
        # live migration (POST /migrate_out): a router that deadline-aborts
        # a hung stream must be able to free this engine's scheduler slot and
        # KV pages without relying on the TCP connection being noticed, and
        # the fleet controller must be able to name a victim stream by its
        # wire id. The meta dict carries what a migration TARGET needs to
        # keep emitting client-shaped chunks (oid/chat/created/model).
        self._live_requests: "dict[str, tuple]" = {}
        # live migration (docs/migration.md; all event-loop-owned):
        # req_id -> {"target", "request_id"} set by a committed migrate_out,
        # consumed by the streaming loop to emit the handoff control event
        self._migrated_out: "dict[str, dict]" = {}
        # req_id -> parked migrated-in continuation ({"q", "task", "snap",
        # "t"}) awaiting the router's POST /migrate_attach
        self._parked: "dict[str, dict]" = {}
        self._mig_session = None  # lazy aiohttp client for /migrate_in ships

    # -- handlers -----------------------------------------------------------

    async def health(self, request: web.Request) -> web.Response:
        if self.draining:
            return web.Response(status=503, text="draining")
        return web.Response(text="")

    async def abort(self, request: web.Request) -> web.Response:
        """Router-initiated abort (POST /abort {"request_id": ...}): free the
        scheduler slot and KV pages of a request whose client-side stream was
        deadline-aborted. Closing the proxy connection only reaches an engine
        that is actively writing; this endpoint reaches a hung one. Abort of
        an unknown or already-finished request is a no-op (200, aborted=false)
        so the router can fire-and-forget."""
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 - malformed abort is harmless
            body = {}
        req_id = body.get("request_id") or request.query.get("request_id")
        if not req_id:
            return web.json_response(
                {"error": {"message": "request_id required"}}, status=400
            )
        entry = self._live_requests.pop(req_id, None)
        for sid in entry[0] if entry else [req_id]:
            self.engine.abort(sid)
        logger.info("abort requested for %s (live=%s)", req_id, entry is not None)
        return web.json_response({"request_id": req_id, "aborted": entry is not None})

    # -- live sequence migration (docs/migration.md) -------------------------

    async def _mig_client(self):
        """Lazy shared client session for shipping snapshots to targets."""
        import aiohttp

        if self._mig_session is None or self._mig_session.closed:
            self._mig_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30, sock_connect=5)
            )
        return self._mig_session

    async def _close_mig_client(self, app=None) -> None:
        if self._mig_session is not None and not self._mig_session.closed:
            await self._mig_session.close()
        self._mig_session = None

    async def migratable(self, request: web.Request) -> web.Response:
        """Controller victim listing: live single-choice streaming requests
        with their progress and migratability verdict. Read-only snapshot of
        scheduler state — racing the device thread can only mis-list a
        request for one tick; the authoritative re-check runs at freeze."""
        mig = getattr(self.engine, "migration", None)
        out: list = []
        if mig is not None:
            from production_stack_tpu.migration import unmigratable_reason

            running = {
                s.seq_id: s for s in list(self.engine.scheduler.running)
            }
            for rid, entry in list(self._live_requests.items()):
                sub_ids, _ts, streaming, _meta = entry
                if not streaming or len(sub_ids) != 1:
                    continue
                seq = running.get(sub_ids[0])
                if seq is None or seq.finished:
                    continue
                reason = unmigratable_reason(seq)
                out.append({
                    "request_id": rid,
                    "output_tokens": len(seq.output_ids),
                    "prompt_tokens": len(seq.prompt_ids),
                    "age_s": round(time.monotonic() - seq.arrival_time, 3),
                    "migratable": reason is None,
                    "reason": reason,
                    # SLO class so the controller's latency-protection
                    # policy can pick batch victims only
                    "priority": _meta.get("priority") or "interactive",
                })
        return web.json_response({"requests": out})

    async def kv_fabric_info(self, request: web.Request) -> web.Response:
        """Fabric discovery: disagg producers, directory pullers, and
        migration sources resolve this engine's fabric listener address (and
        its generation/dtype handshake facts) from here."""
        srv = getattr(self.engine, "_fabric_server", None)
        if srv is None:
            return web.json_response({"enabled": False})
        return web.json_response({
            "enabled": True,
            "addr": srv.address,
            "generation": srv.generation,
            "quant": srv.quant,
            "page_size": srv.page_size,
        })

    async def migrate_out(self, request: web.Request) -> web.Response:
        """Freeze a running stream, ship its snapshot to the target engine's
        /migrate_in, then commit (the stream ends with the handoff control
        event the router splices on) or roll back (the sequence resumes
        decoding locally — nothing was client-visible)."""
        mig = getattr(self.engine, "migration", None)
        if mig is None:
            return web.json_response(
                {"migrated": False, "error": "migration disabled"}, status=501
            )
        try:
            body = await request.json()
            rid = body["request_id"]
            target = str(body["target_url"]).rstrip("/")
        except (KeyError, TypeError, ValueError):
            return web.json_response(
                {"migrated": False,
                 "error": "request_id and target_url required"},
                status=400,
            )
        entry = self._live_requests.get(rid)
        if entry is None:
            return web.json_response(
                {"migrated": False, "error": f"request {rid!r} is not live"},
                status=409,
            )
        sub_ids, _ts, streaming, meta = entry
        if not streaming or len(sub_ids) != 1:
            return web.json_response(
                {"migrated": False,
                 "error": "only single-choice streaming requests migrate"},
                status=409,
            )
        from production_stack_tpu.migration import (
            MigrationError,
            snapshot_to_wire,
        )

        loop = asyncio.get_running_loop()
        snap_meta = {**meta, "request_id": rid}
        # fabric handoff: resolve the target's fabric listener FIRST so the
        # freeze can ship the page chain engine-to-engine (zero shared-tier
        # I/O); an unresolvable/disabled fabric degrades to the tier save
        # inside _freeze
        fabric_addr = None
        if getattr(self.engine, "_fabric_client", None) is not None:
            try:
                session = await self._mig_client()
                async with session.get(f"{target}/kv_fabric") as resp:
                    if resp.status == 200:
                        info = await resp.json()
                        if info.get("enabled"):
                            fabric_addr = info.get("addr")
            except Exception as e:  # noqa: BLE001 - tier path covers it
                logger.debug("fabric resolve for %s failed: %s", target, e)
        try:
            # device-thread work off the event loop (GC001 discipline)
            snap = await loop.run_in_executor(
                None,
                lambda: mig.freeze_and_snapshot(
                    sub_ids[0], snap_meta, fabric_addr
                ),
            )
        except MigrationError as e:
            return web.json_response(
                {"migrated": False, "error": str(e)}, status=409
            )
        ok, detail = False, ""
        try:
            session = await self._mig_client()
            async with session.post(
                f"{target}/migrate_in", data=snapshot_to_wire(snap),
                headers={"Content-Type": "application/octet-stream"},
            ) as resp:
                detail = (await resp.text())[:200]
                ok = resp.status == 200
        except Exception as e:  # noqa: BLE001 - any ship failure rolls back
            detail = repr(e)
        if not ok:
            # fallback: the sequence re-enters the running set and keeps
            # streaming locally — the client never noticed the attempt
            await loop.run_in_executor(None, mig.rollback, sub_ids[0])
            logger.warning(
                "migrate_out %s -> %s refused: %s", rid, target, detail
            )
            return web.json_response(
                {"migrated": False, "error": detail or "target refused"},
                status=502,
            )
        # control-event metadata BEFORE commit: the commit's terminal emit
        # races the streaming loop's pop of this entry. Janitor: when the
        # client disconnected between freeze and commit, the streaming
        # handler already tore down (its pop ran before this set) and the
        # terminal emit finds no consumer — nothing would ever pop the
        # entry, and a reused wire id would see a stale handoff target
        self._migrated_out[rid] = {"target": target, "request_id": rid}
        loop.call_later(60.0, self._migrated_out.pop, rid, None)
        await loop.run_in_executor(
            None, mig.commit, sub_ids[0], len(snap.page_hashes)
        )
        logger.info(
            "migrated %s -> %s (%d pages restorable)",
            rid, target, len(snap.page_hashes),
        )
        return web.json_response({
            "migrated": True, "target": target,
            "pages_moved": len(snap.page_hashes),
        })

    async def migrate_in(self, request: web.Request) -> web.Response:
        """Accept a sealed snapshot and park the continuation: KV blobs
        prefetch into the local tiers, the sequence re-admits through the
        ordinary prefix-cache path (shipped pages share, the tail recomputes
        deterministically), and outputs buffer until /migrate_attach."""
        mig = getattr(self.engine, "migration", None)
        if mig is None:
            return web.json_response(
                {"accepted": False, "error": "migration disabled"}, status=501
            )
        if self.draining:
            return web.json_response(
                {"accepted": False, "error": "draining"}, status=503
            )
        if self.engine.is_sleeping:
            return web.json_response(
                {"accepted": False, "error": "sleeping"}, status=503
            )
        saturated = getattr(self.engine, "saturated", None)
        if saturated is not None and saturated():
            # a saturated target must refuse extra work — 429 tells the
            # controller to pick a cooler target (breaker-neutral, like any
            # shed)
            return _shed_response(
                getattr(self.engine, "shed_retry_after", lambda: 1.0)(),
                "engine saturated; pick a cooler migration target",
            )
        from production_stack_tpu.kvoffload.serde import KVIntegrityError
        from production_stack_tpu.migration import (
            continuation_params,
            snapshot_from_wire,
        )

        data = await request.read()
        try:
            snap = snapshot_from_wire(data)
            params = continuation_params(snap)
        except (KVIntegrityError, ValueError, KeyError, TypeError) as e:
            return web.json_response(
                {"accepted": False, "error": f"bad snapshot: {e}"}, status=400
            )
        if snap.model != self.cfg.name:
            return web.json_response(
                {"accepted": False,
                 "error": f"model mismatch: {snap.model!r} != {self.cfg.name!r}"},
                status=409,
            )
        rid = snap.request_id
        if rid in self._parked or rid in self._live_requests:
            return web.json_response(
                {"accepted": False, "error": f"{rid!r} already live here"},
                status=409,
            )
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        task = loop.create_task(self._pump_migrated(snap, params, q))
        self._parked[rid] = {
            "q": q, "task": task, "snap": snap, "t": time.monotonic(),
        }
        # chained migration: the continuation is itself a live, migratable
        # stream (an engine holding migrated-in work must still evacuate).
        # prior_completion accumulates tokens emitted on EVERY previous hop:
        # a re-freeze snapshots only THIS engine's output_ids, so without
        # the running total a 2+-hop stream's final usage would drop the
        # first hop's tokens
        self._live_requests[rid] = (
            [rid], time.monotonic(), True,
            {**snap.meta,
             "prior_completion": snap.output_len
             + int(snap.meta.get("prior_completion") or 0)},
        )
        # a router that died mid-handoff must not leak a decoding sequence:
        # unattached continuations abort after the timeout
        loop.call_later(
            max(1.0, getattr(self.cfg, "migrate_attach_timeout_s", 30.0)),
            self._expire_parked, rid,
        )
        mig.note_migrate_in()
        return web.json_response({
            "accepted": True, "request_id": rid,
            "restorable_pages": len(snap.page_hashes),
        })

    async def _pump_migrated(self, snap, params, q: asyncio.Queue) -> None:
        """Parked continuation driver: prefetch the snapshot's KV blobs into
        the local tiers (executor — tier reads block), then resume decoding
        and buffer outputs for the attach stream. shed_exempt: a migrated
        stream is mid-flight — shedding it would drop a committed stream."""
        loop = asyncio.get_running_loop()
        mig = self.engine.migration
        try:
            if snap.page_hashes and snap.page_size == self.cfg.page_size:
                await loop.run_in_executor(
                    None, mig.prefetch_pages, snap.page_hashes
                )
            kwargs = {}
            if self._engine_accepts_priority:
                # the continuation keeps its SLO class across the hop, so a
                # migrated batch stream stays a latency-protection victim on
                # the target too
                p = snap.meta.get("priority")
                kwargs["priority"] = (
                    p if p in ("interactive", "batch") else "interactive"
                )
            async for out in self.engine.generate(
                snap.request_id, prompt_token_ids=snap.tokens, params=params,
                shed_exempt=True, **kwargs,
            ):
                await q.put(out)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 - surfaced on the attach stream
            await q.put(e)
        finally:
            self._live_requests.pop(snap.request_id, None)
            await q.put(None)

    def _expire_parked(self, rid: str) -> None:
        parked = self._parked.pop(rid, None)
        if parked is None:
            return  # attached (or already expired)
        parked["task"].cancel()
        self.engine.abort(rid)
        mig = getattr(self.engine, "migration", None)
        if mig is not None:
            mig.failures += 1
        logger.warning(
            "migrated-in continuation %s expired unattached; aborted", rid
        )

    async def migrate_attach(self, request: web.Request) -> web.StreamResponse:
        """Stream a parked continuation in the client wire shape. The final
        usage block reports WHOLE-request totals (pre- + post-migration), so
        the spliced stream is indistinguishable from an unmigrated one."""
        if getattr(self.engine, "migration", None) is None:
            return web.json_response(
                {"error": {"message": "migration disabled"}}, status=501
            )
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 - allow query-only attaches
            body = {}
        rid = body.get("request_id") or request.query.get("request_id")
        if not rid:
            return web.json_response(
                {"error": {"message": "request_id required"}}, status=400
            )
        # tiny grace for reordering: the source commits (ending its stream)
        # only after our /migrate_in returned, so the parked entry normally
        # exists before any attach arrives
        deadline = time.monotonic() + 10.0
        parked = self._parked.pop(rid, None)
        while parked is None and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
            parked = self._parked.pop(rid, None)
        if parked is None:
            return web.json_response(
                {"error": {"message": f"no parked continuation for {rid!r}"}},
                status=404,
            )
        snap, q = parked["snap"], parked["q"]
        meta = snap.meta
        chat = bool(meta.get("chat"))
        oid = meta.get("oid") or (("chatcmpl-" if chat else "cmpl-") + rid)
        created = int(meta.get("created") or time.time())
        model = meta.get("model") or snap.model
        kind = "chat.completion" if chat else "text_completion"
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Request-Id": rid,
            },
        )
        await resp.prepare(request)

        async def send(obj: dict):
            await resp.write(f"data: {json.dumps(obj)}\n\n".encode())

        new_tokens = 0
        try:
            while True:
                out = await q.get()
                if out is None:
                    break
                if isinstance(out, Exception):
                    await send({"error": {
                        "message": f"migrated continuation failed: {out}",
                        "type": "upstream_error", "code": 502,
                    }})
                    await resp.write_eof()
                    return resp
                if (
                    out.finished
                    and out.finish_reason == "migrated"
                    and (mi := self._migrated_out.pop(rid, None)) is not None
                ):
                    # chained migration: this continuation moved AGAIN —
                    # hand the splice the next hop and end this leg
                    await send({"pstpu_migration": mi})
                    await resp.write_eof()
                    return resp
                new_tokens = out.completion_tokens
                if out.finished and out.finish_reason in (
                    "abort", "error", "shed"
                ):
                    await send({"error": {
                        "message": (
                            "migrated continuation ended with "
                            f"{out.finish_reason!r}"
                        ),
                        "type": "upstream_error", "code": 502,
                    }})
                    await resp.write_eof()
                    return resp
                if chat:
                    delta = (
                        {"content": out.text_delta} if out.text_delta else {}
                    )
                    choice = {"index": 0, "delta": delta,
                              "finish_reason": out.finish_reason}
                    obj = "chat.completion.chunk"
                else:
                    choice = {"index": 0, "text": out.text_delta,
                              "logprobs": None,
                              "finish_reason": out.finish_reason}
                    obj = "text_completion"
                await send({
                    "id": oid, "object": obj, "created": created,
                    "model": model, "choices": [choice],
                })
            prompt_tokens = int(meta.get("prompt_tokens") or snap.prompt_len)
            # whole-request total: every previous hop's tokens + the tokens
            # already emitted when THIS hop froze + this continuation's
            completion = (
                int(meta.get("prior_completion") or 0)
                + snap.output_len + new_tokens
            )
            await send({
                "id": oid, "object": f"{kind}.chunk" if chat else kind,
                "created": created, "model": model, "choices": [],
                "usage": {
                    "prompt_tokens": prompt_tokens,
                    "completion_tokens": completion,
                    "total_tokens": prompt_tokens + completion,
                },
            })
            await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            # the splicing router (or client) went away: reclaim the seq
            parked["task"].cancel()
            self.engine.abort(rid)
            raise
        await resp.write_eof()
        return resp

    async def drain(self, timeout: float = 30.0) -> None:
        """Stop accepting generation work and wait for the engine to go
        idle (in-flight requests complete) or ``timeout`` to pass."""
        self.draining = True
        # SIGTERM anomaly dump FIRST (forced — this process is going away):
        # the pre-drain scheduler/KV window is what a rolling-restart
        # postmortem needs, and waiting out the drain would overwrite it
        from production_stack_tpu.tracing import get_flightrecorder

        get_flightrecorder().dump("sigterm_drain", force=True)
        logger.info("draining: refusing new requests, waiting for %d in flight",
                    self.engine.scheduler.num_running())
        deadline = time.time() + timeout
        while time.time() < deadline and self.engine.scheduler.has_work():
            await asyncio.sleep(0.2)
        if self.engine.scheduler.has_work():
            logger.warning("drain timeout: %d request(s) still running",
                           self.engine.scheduler.num_running())
        # warm-start manifest: spill the hot working set AFTER in-flight work
        # finished (their pages are registered by now), so the next
        # incarnation restores it instead of recomputing (warm restarts)
        spill = getattr(self.engine, "warm_spill", None)
        if spill is not None:
            try:
                n = await asyncio.get_running_loop().run_in_executor(None, spill)
                if n:
                    logger.info("drain: warm-start manifest spilled (%d pages)", n)
            except Exception:  # noqa: BLE001 - shutdown keeps going
                logger.exception("drain: warm-start spill failed")

    async def version(self, request: web.Request) -> web.Response:
        return web.json_response({"version": __version__})

    async def models(self, request: web.Request) -> web.Response:
        data = [
            {
                "id": self.cfg.name,
                "object": "model",
                "created": int(self.start_time),
                "owned_by": "production-stack-tpu",
                "max_model_len": self.cfg.max_model_len,
            }
        ]
        # loaded LoRA adapters appear as servable models with a parent pointer
        # (vLLM convention; the reference LoraAdapter controller and router
        # model discovery both read this listing)
        for name in self.engine.list_lora_adapters():
            data.append(
                {
                    "id": name,
                    "object": "model",
                    "created": int(self.start_time),
                    "owned_by": "production-stack-tpu",
                    "parent": self.cfg.name,
                    "max_model_len": self.cfg.max_model_len,
                }
            )
        return web.json_response({"object": "list", "data": data})

    async def tokenize(self, request: web.Request) -> web.Response:
        body = await request.json()
        text = body.get("prompt")
        if text is None and "messages" in body:
            text = self.engine.tokenizer.apply_chat_template(body["messages"])
        ids = self.engine.tokenizer.encode(text or "")
        return web.json_response(
            {"tokens": ids, "count": len(ids), "max_model_len": self.cfg.max_model_len}
        )

    async def detokenize(self, request: web.Request) -> web.Response:
        body = await request.json()
        return web.json_response({"prompt": self.engine.tokenizer.decode(body.get("tokens", []))})

    async def metrics(self, request: web.Request) -> web.Response:
        s = self.engine.stats()
        m = self.cfg.name
        lines = []

        def emit(name: str, kind: str, value, help_: str = ""):
            lines.append(f"# HELP vllm:{name} {help_ or name}")
            lines.append(f"# TYPE vllm:{name} {kind}")
            lines.append(f'vllm:{name}{{model_name="{m}"}} {value}')

        emit("num_requests_running", "gauge", s["num_requests_running"])
        emit("num_requests_waiting", "gauge", s["num_requests_waiting"])
        emit("num_requests_swapped", "gauge", s.get("num_requests_swapped", 0))
        emit("num_preemptions_total", "counter",
             s.get("num_preemptions_total", 0))
        # overload surface: saturation state + load sheds (admission control)
        emit("engine_saturated", "gauge", s.get("engine_saturated", 0),
             "1 while the waiting queue is at its max_waiting_seqs bound")
        emit("num_requests_shed_total", "counter",
             s.get("num_requests_shed_total", 0),
             "generation requests shed with 429 (queue full or queue deadline)")
        # per-SLO-class overload surface (docs/failure-handling.md priority
        # classes): shed order, batch-early saturation, and the interactive
        # latency signal the fleet controller's latency protection scrapes
        emit("num_requests_shed_interactive_total", "counter",
             s.get("num_requests_shed_interactive_total", 0),
             "interactive-class requests shed with 429")
        emit("num_requests_shed_batch_total", "counter",
             s.get("num_requests_shed_batch_total", 0),
             "batch-class requests shed with 429")
        emit("engine_saturated_batch", "gauge",
             s.get("engine_saturated_batch", 0),
             "1 while batch-class admission is shedding (interactive reserve)")
        emit("interactive_ttft_p99_ms", "gauge",
             s.get("interactive_ttft_p99_ms", 0.0),
             "p99 TTFT over the recent interactive ok-request window")
        emit("interactive_itl_p99_ms", "gauge",
             s.get("interactive_itl_p99_ms", 0.0),
             "p99 inter-token latency over the recent interactive window")
        emit("tensor_parallel_degree", "gauge",
             s.get("tensor_parallel", 1),
             "tp mesh-axis size of the serving mesh (chips per replica)")
        emit("gpu_cache_usage_perc", "gauge", s["gpu_cache_usage_perc"])
        emit("gpu_prefix_cache_hit_rate", "gauge", s["gpu_prefix_cache_hit_rate"])
        emit("gpu_prefix_cache_hits_total", "counter", s["gpu_prefix_cache_hits_total"])
        emit("gpu_prefix_cache_queries_total", "counter", s["gpu_prefix_cache_queries_total"])
        emit("prompt_tokens_total", "counter", s["prompt_tokens_total"])
        emit("generation_tokens_total", "counter", s["generation_tokens_total"])
        emit("decode_dispatches_total", "counter", s["decode_dispatches_total"])
        emit("decode_chained_dispatches_total", "counter",
             s["decode_chained_dispatches_total"])
        emit("runahead_prefill_dispatches_total", "counter",
             s.get("runahead_prefill_dispatches_total", 0))
        for k in sorted(s):  # kv offload / transfer / spec / warm-start / loop
            if k.startswith(("kv_", "spec_decode_", "engine_loop_", "warm_start_")):
                kind = "counter" if k.endswith("_total") else "gauge"
                emit(k, kind, s[k])
        # TTFT hop breakdown for streaming requests (accept->submit->first
        # token->first SSE write), p50/p99 over the sample window. ONE TYPE
        # line per metric name — a duplicate would fail the whole Prometheus
        # scrape
        hops = _ttft_hop_quantiles()
        # engine-side admission wait (arrival -> first prefill dispatch):
        # the slice of submit_to_first_token a chained decode dispatch can
        # inflate; exposed so the bench can prove the adaptive chain cap
        waits = getattr(self.engine, "admission_wait_ms", None)
        if waits:
            # the engine thread appends concurrently; iterating a mutating
            # deque raises RuntimeError — snapshot with a bounded retry
            s_w = None
            for _ in range(3):
                try:
                    s_w = sorted(waits)
                    break
                except RuntimeError:
                    continue
            if s_w:
                hops["admission_wait"] = {
                    "p50": s_w[len(s_w) // 2],
                    "p99": s_w[min(len(s_w) - 1, int(len(s_w) * 0.99))],
                }
        for hop, qs in hops.items():
            lines.append(f"# TYPE vllm:ttft_hop_{hop}_ms gauge")
            for q, v in qs.items():
                lines.append(
                    f'vllm:ttft_hop_{hop}_ms{{model_name="{m}",quantile="{q}"}} '
                    f"{round(v, 3)}"
                )
        # distribution histograms (dashboard TTFT/latency heatmap panels)
        lines.extend(_ttft_hist.render(f'model_name="{m}"'))
        lines.extend(_latency_hist.render(f'model_name="{m}"'))
        # per-phase histograms (tracing subsystem): queue wait, prefill,
        # time-per-output-token, offload restore — the dashboard's
        # phase-breakdown panels and bench.py's attribution read these
        from production_stack_tpu.tracing import (
            render_collector_metrics,
            render_flightrecorder_metrics,
            render_phase_histograms,
        )

        # live-migration surface (docs/migration.md): counters + the
        # freeze-to-commit duration histogram
        mig = getattr(self.engine, "migration", None)
        if mig is not None:
            ms = mig.stats()
            for k in sorted(ms):
                emit(k, "counter", ms[k])
            lines.extend(mig.duration_hist.render(f'model_name="{m}"'))
        # KV fabric surface (docs/kv-fabric.md): stream/pull latency
        # histograms + the per-peer probed-bandwidth gauge the disagg router
        # and fleet controller scrape for transfer-cost-aware placement.
        # Counters (kv_fabric_*_total) already rendered via engine.stats()
        fab = getattr(self.engine, "_fabric_client", None)
        if fab is not None:
            lines.extend(fab.push_hist.render(f'model_name="{m}"'))
            lines.extend(fab.pull_hist.render(f'model_name="{m}"'))
            peers = fab.probe_cache.snapshot()
            lines.append(
                "# HELP vllm:kv_fabric_peer_bandwidth_bytes_per_sec "
                "probed engine-to-engine fabric bandwidth per peer"
            )
            lines.append(
                "# TYPE vllm:kv_fabric_peer_bandwidth_bytes_per_sec gauge"
            )
            if not peers:
                # zero-valued placeholder keeps the name scrapeable (and the
                # dashboard panel non-empty) before the first probe completes
                lines.append(
                    f"vllm:kv_fabric_peer_bandwidth_bytes_per_sec"
                    f'{{model_name="{m}",peer="none"}} 0'
                )
            for addr, link in sorted(peers.items()):
                lines.append(
                    f"vllm:kv_fabric_peer_bandwidth_bytes_per_sec"
                    f'{{model_name="{m}",peer="{addr}"}} '
                    f"{round(link.bandwidth, 1)}"
                )
        lines.extend(render_phase_histograms(f'model_name="{m}"'))
        # span-loss + flight-recorder health (trace debugging is only
        # trustworthy when its own drops are measurable)
        lines.extend(render_collector_metrics(f'model_name="{m}"'))
        lines.extend(render_flightrecorder_metrics(f'model_name="{m}"'))
        # TPU device telemetry (engine/devicemon.py): HBM in use/limit per
        # device, KV pool vs headroom, compile cache + seconds, duty cycle
        try:
            lines.extend(self.devmon.metrics_lines(m))
        except Exception:  # noqa: BLE001 - telemetry must never break a scrape
            logger.exception("device telemetry sampling failed")
        return web.Response(text="\n".join(lines) + "\n", content_type="text/plain")

    async def slo_records(self, request: web.Request) -> web.Response:
        """Per-request SLO terminal records since a cursor (docs/
        observability.md). The router's stats scraper polls this with
        ``?since=<last seq>`` each scrape interval and aggregates the
        records into per-model/backend SLO attainment counters; the log is
        a bounded ring, so a scraper further behind than its capacity sees
        a gap (records dropped, not blocked)."""
        try:
            since = int(request.query.get("since", "0"))
        except (TypeError, ValueError):
            return web.json_response({"error": "since must be an int"}, status=400)
        log = getattr(self.engine, "slo_records", None)
        records: list = []
        # an exhausted snapshot retry must NOT report head=0 — the scraper
        # reads head < cursor as "engine restarted" and would reset its
        # cursor, double-counting every retained record next round; head ==
        # the caller's cursor is the safe "nothing new" answer
        head = since
        if log:
            # the engine thread appends concurrently; iterating a mutating
            # deque raises RuntimeError — snapshot with a bounded retry
            for _ in range(3):
                try:
                    snap = list(log)
                    # max, not snap[-1]: the device thread and the event
                    # loop (api-shed records) both append, so the tail can
                    # momentarily be out of seq order
                    head = max((r["seq"] for r in snap), default=0)
                    records = [r for r in snap if r["seq"] > since]
                    break
                except RuntimeError:
                    continue
        elif log is not None:
            head = 0  # empty log: a true fresh-counter signal is correct
        next_cursor = max((r["seq"] for r in records), default=since)
        return web.json_response({
            "model": self.cfg.name,
            "since": since,
            "next": next_cursor,
            # current max record seq: a head BELOW the caller's cursor means
            # this process restarted (fresh counter) — the scraper resets its
            # cursor instead of waiting for the new counter to catch up
            "head": head,
            "records": records,
        })

    async def flightrecorder(self, request: web.Request) -> web.Response:
        """Flight-recorder export (debug surface; docs/observability.md).
        Filters: ?request_id= ?trace_id= ?kind= ?since_step= ?until_step=
        ?limit=."""
        from production_stack_tpu.tracing import flightrecorder

        payload, status = flightrecorder.export_for_query(request.query)
        return web.json_response(payload, status=status)

    async def stats(self, request: web.Request) -> web.Response:
        """JSON engine state snapshot (saturation, queue depths, KV pool,
        shed counters) — the machine-readable twin of /metrics for
        autoscalers and the router's shed-aware logic (docs/failure-handling
        overload section)."""
        s = dict(self.engine.stats())
        s["saturation"] = {
            "saturated": bool(s.get("engine_saturated", 0)),
            "max_waiting_seqs": getattr(self.cfg, "max_waiting_seqs", 0),
            "queue_deadline_s": getattr(self.cfg, "queue_deadline_s", 0.0),
            "retry_after_s": getattr(
                self.engine, "shed_retry_after", lambda: 1.0
            )(),
            "draining": self.draining,
        }
        return web.json_response(s)

    async def traces(self, request: web.Request) -> web.Response:
        """Span ring-buffer export (read-only debug surface; docs/tracing.md).
        ?trace_id= filters to one trace, ?limit= caps the trace count."""
        from production_stack_tpu.tracing import export_for_query

        payload, status = export_for_query(request.query)
        return web.json_response(payload, status=status)

    async def metrics_reset(self, request: web.Request) -> web.Response:
        """Clear the TTFT hop sample windows (debug/bench endpoint): per-phase
        quantiles require each phase to start from an empty window, else the
        gauges pool samples from differently-loaded phases. Counters and
        serving stats are untouched."""
        from production_stack_tpu.tracing import (
            get_collector,
            get_flightrecorder,
            reset_phase_histograms,
        )

        _ttft_hops.clear()
        _ttft_hist.reset()
        _latency_hist.reset()
        reset_phase_histograms()
        get_collector().reset()
        get_flightrecorder().reset()
        waits = getattr(self.engine, "admission_wait_ms", None)
        if waits is not None:
            waits.clear()
        return web.json_response({"status": "ok"})

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
            messages = body.get("messages", [])
            if not isinstance(messages, list):
                raise ValueError("'messages' must be a list")
            tools, tool_style = self._resolve_tools(body)
        except (ValueError, TypeError) as e:
            return web.json_response({"error": {"message": f"invalid request: {e}"}}, status=400)
        prompt = self.engine.tokenizer.apply_chat_template(messages, tools=tools)
        return await self._generate(
            request, body, prompt, chat=True, tool_style=tool_style
        )

    def _resolve_tools(self, body: dict) -> "tuple[Optional[list], Optional[str]]":
        """(tools to render into the template, parser style or None).

        tool_choice: "none" drops the schemas entirely; a named function
        narrows the rendered schemas to that tool (the strongest steer
        available without constrained decoding); "auto"/"required" render
        all. Reference behavior comes from vLLM's --tool-call-parser flags
        (/root/reference/tutorials/13-tool-enabled-installation.md)."""
        tools = body.get("tools")
        if tools is not None:
            if not isinstance(tools, list):
                raise ValueError("'tools' must be a list")
            for t in tools:
                # validate shape HERE, where ValueError maps to a 400 —
                # malformed entries must not crash template rendering later
                if not (
                    isinstance(t, dict)
                    and isinstance(t.get("function"), dict)
                    and isinstance(t["function"].get("name"), str)
                ):
                    raise ValueError(
                        "each tool must be {'type': 'function', "
                        "'function': {'name': ..., ...}}"
                    )
        for msg in body.get("messages", []):
            for c in (msg.get("tool_calls") or []) if isinstance(msg, dict) else []:
                fn = c.get("function") if isinstance(c, dict) else None
                if not (isinstance(fn, dict) and isinstance(fn.get("name"), str)
                        and isinstance(fn.get("arguments", ""), str)):
                    raise ValueError(
                        "message tool_calls must carry function.name and "
                        "string function.arguments"
                    )
        choice = body.get("tool_choice", "auto" if tools else "none")
        if not tools or choice == "none" or self.cfg.tool_call_parser == "off":
            return None, None
        if isinstance(choice, dict):
            name = (choice.get("function") or {}).get("name")
            named = [
                t for t in tools
                if (t.get("function") or {}).get("name") == name
            ]
            if not named:
                raise ValueError(f"tool_choice names unknown tool {name!r}")
            tools = named
        elif choice not in ("auto", "required"):
            raise ValueError(f"invalid tool_choice {choice!r}")
        return tools, self.cfg.tool_call_parser

    async def completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except (ValueError, TypeError) as e:
            return web.json_response({"error": {"message": f"invalid request: {e}"}}, status=400)
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        return await self._generate(request, body, prompt, chat=False)

    async def _generate(
        self, request: web.Request, body: dict, prompt: str, chat: bool,
        tool_style: Optional[str] = None,
    ) -> web.StreamResponse:
        t_accept = time.perf_counter()
        t_accept_wall = time.time()
        # distributed tracing: adopt the router's traceparent (its sampled
        # flag wins) or root a new trace for engine-direct requests; the
        # engine.request span context parents every per-phase span the
        # engine loop records for this request (docs/tracing.md)
        from production_stack_tpu.tracing import get_collector

        _collector = get_collector()
        trace_ctx = _collector.root_from_headers(request.headers).child()
        if self.draining:
            return web.json_response(
                {"error": {"message": "engine is draining for shutdown"}},
                status=503,
            )
        if self.engine.is_sleeping:
            return web.json_response({"error": "engine is sleeping"}, status=503)
        # per-request SLO class, parsed before the saturation check so the
        # shed watermark is class-aware (batch saturates the interactive
        # reserve early — see scheduler.saturated)
        priority = _request_priority(request.headers, body)
        # admission control: a full waiting queue sheds HERE, before any
        # scheduler state exists for the request — a clean 429 + Retry-After
        # the router can fail over on (duck-typed: fakes/tests may lack it)
        saturated = getattr(self.engine, "saturated", None)
        if saturated is not None and (
            saturated(priority) if self._saturated_accepts_priority
            else saturated()
        ):
            # event-loop-owned counter (the engine thread owns requests_shed;
            # two writers on one dict slot would drop increments)
            if hasattr(self.engine, "api_requests_shed"):
                self.engine.api_requests_shed += 1
            note_shed = getattr(self.engine, "note_api_shed", None)
            if note_shed is not None:
                # flight-recorder shed event + burst trigger + SLO terminal
                # record (no Sequence exists for a fast-path shed)
                try:
                    note_shed(
                        request.headers.get("X-Request-Id"),
                        priority=priority,
                    )
                except TypeError:  # duck-typed engine predating priority
                    note_shed(request.headers.get("X-Request-Id"))
            retry = getattr(self.engine, "shed_retry_after", lambda: 1.0)()
            return _shed_response(
                retry,
                f"engine saturated: {self.engine.scheduler.num_waiting()} "
                "requests already waiting",
            )
        model = body.get("model", self.cfg.name)
        lora_name = None
        if model != self.cfg.name:
            if self.engine.lora is not None and self.engine.lora.is_adapter(model):
                lora_name = model
            else:
                return web.json_response(
                    {"error": {"message": f"model {model!r} does not exist",
                               "type": "NotFoundError", "code": 404}},
                    status=404,
                )
        req_id = request.headers.get("X-Request-Id") or f"req-{uuid.uuid4().hex[:16]}"
        try:
            params = _sampling_params(body, vocab_size=self._vocab_size())
        except (ValueError, TypeError) as e:
            return web.json_response(
                {"error": {"message": f"invalid request: {e}"}}, status=400
            )
        if not (-2.0 <= params.presence_penalty <= 2.0
                and -2.0 <= params.frequency_penalty <= 2.0
                and params.repetition_penalty > 0):
            return web.json_response(
                {"error": {"message": "penalties out of range: presence/frequency in [-2, 2], repetition > 0"}},
                status=400,
            )
        if (
            params.wants_penalties or params.logit_bias or params.min_tokens > 0
        ) and self.cfg.speculative_k:
            return web.json_response(
                {"error": {"message": "sampling penalties, logit_bias, and "
                                      "min_tokens are not supported with "
                                      "speculative decoding"}},
                status=400,
            )
        # logprobs: completions takes an int (top count), chat takes
        # logprobs=true + top_logprobs=N; the chosen token's logprob is
        # always included when enabled
        lp_count = None
        if chat:
            if body.get("logprobs"):
                lp_count = int(body.get("top_logprobs") or 0)
        elif body.get("logprobs") is not None:
            lp_count = int(body["logprobs"])
        if lp_count is not None:
            from production_stack_tpu.ops.sampling import TOP_LOGPROBS

            if not 0 <= lp_count <= TOP_LOGPROBS:
                return web.json_response(
                    {"error": {"message": f"logprobs must be in [0, {TOP_LOGPROBS}]"}},
                    status=400,
                )
            if self.cfg.speculative_k:
                return web.json_response(
                    {"error": {"message": "logprobs are not supported with speculative decoding"}},
                    status=400,
                )
            params.logprobs = lp_count
        stream = bool(body.get("stream", False))
        created = int(time.time())
        kind = "chat.completion" if chat else "text_completion"
        oid = ("chatcmpl-" if chat else "cmpl-") + req_id

        # Tokenize and validate *before* streaming starts — generate() is an
        # async generator, so errors inside it would surface after the 200.
        prompt_ids = self.engine.tokenizer.encode(prompt)
        if len(prompt_ids) + 1 > self.cfg.max_model_len:
            return web.json_response(
                {
                    "error": {
                        "message": (
                            f"prompt has {len(prompt_ids)} tokens, "
                            f"max_model_len is {self.cfg.max_model_len}"
                        )
                    }
                },
                status=400,
            )
        n = 1 if body.get("n") is None else int(body["n"])
        best_of = n if body.get("best_of") is None else int(body["best_of"])
        if not 1 <= n <= 64 or best_of != n:
            return web.json_response(
                {"error": {"message": f"n must be in [1, 64] and best_of == n, got n={n} best_of={best_of}"}},
                status=400,
            )
        # n parallel samples: one engine sequence per choice. Sub-sequences
        # get '#i'-suffixed ids (plain req_id when n == 1 so request tracing
        # and the reference-format routing logs stay stable). Siblings launch
        # AFTER choice 0's prefill completes: the scheduler registers the
        # prompt's pages in the prefix cache at that point, so siblings share
        # the prompt KV instead of re-prefilling it n times.
        sub_ids = [req_id] if n == 1 else [f"{req_id}#{i}" for i in range(n)]
        # register for POST /abort; engine.abort is idempotent, so a stale
        # entry (rare engine-internal error path) only costs dict space.
        # Bound growth by evicting the oldest entry ONLY when it is clearly a
        # leak (hours old) — under legitimate >8k-concurrent load the oldest
        # entry is a live long-running stream whose abortability must survive
        if len(self._live_requests) > 8192:
            oldest = next(iter(self._live_requests))
            if time.monotonic() - self._live_requests[oldest][1] > 3600:
                self._live_requests.pop(oldest)
        self._live_requests[req_id] = (
            sub_ids, time.monotonic(), stream,
            # presentation meta a migration target needs to keep emitting
            # client-shaped chunks (and honest whole-request usage totals);
            # priority rides along so /migratable can class-filter victims
            # and a migrated continuation keeps its SLO class
            {"oid": oid, "chat": chat, "created": created, "model": model,
             "prompt_tokens": len(prompt_ids), "priority": priority},
        )

        def _gen(sid):
            kwargs = dict(
                prompt_token_ids=prompt_ids, params=params, lora_name=lora_name
            )
            # duck-typed engines (tests, fakes) may predate the trace kwarg;
            # they still get the engine.request span, just no phase spans.
            # For n > 1 only choice 0 carries the context: n concurrent
            # sibling phase-span sets under one engine.request would sum past
            # the parent's wall time and corrupt the self-time attribution,
            # so the trace follows one representative sequence
            if self._engine_accepts_trace and sid == sub_ids[0]:
                kwargs["trace"] = trace_ctx
            # parallel-sampling siblings (choice > 0) launch only after
            # choice 0's first output — their request is mid-flight, so they
            # are exempt from engine-side load shedding (choice 0's own shed
            # still 429s the whole request cleanly and aborts them)
            if self._engine_accepts_shed_exempt and sid != sub_ids[0]:
                kwargs["shed_exempt"] = True
            if self._engine_accepts_priority:
                kwargs["priority"] = priority
            return self.engine.generate(sid, **kwargs)

        def _shed_whole_request() -> web.Response:
            """Queue-deadline shed before any output: abort every choice and
            answer 429 + Retry-After for the request as a whole."""
            self._live_requests.pop(req_id, None)
            for sid in sub_ids:
                self.engine.abort(sid)
            return _shed_response(
                getattr(self.engine, "shed_retry_after", lambda: 1.0)(),
                "request shed: queue deadline exceeded before dispatch",
            )

        t_submit = time.perf_counter()
        if n == 1:
            gens = [_gen(sub_ids[0])]
        else:
            prefilled = asyncio.Event()

            async def first(sid):
                try:
                    async for out in _gen(sid):
                        prefilled.set()
                        yield out
                finally:
                    prefilled.set()  # error/abort must not wedge siblings

            async def sibling(sid):
                await prefilled.wait()
                async for out in _gen(sid):
                    yield out

            gens = [first(sub_ids[0])] + [sibling(sid) for sid in sub_ids[1:]]
        gen = gens[0]

        if not stream:
            t_first_box = [None]

            async def collect(i, g):
                text, finish_reason, last = [], None, None
                tok_ids, lp_entries = [], []
                async for out in g:
                    if t_first_box[0] is None:
                        t_first_box[0] = time.perf_counter()
                    text.append(out.text_delta)
                    last = out
                    if out.logprobs is not None:
                        tok_ids.extend(out.token_ids)
                        lp_entries.extend(out.logprobs)
                    if out.finished:
                        finish_reason = out.finish_reason
                return i, "".join(text), finish_reason, last, tok_ids, lp_entries

            try:
                results = await asyncio.gather(
                    *(collect(i, g) for i, g in enumerate(gens))
                )
            except (Exception, asyncio.CancelledError):
                # one failed choice (or a client disconnect) must not leave
                # its n-1 siblings generating — and holding KV pages — until
                # their own completion
                self._live_requests.pop(req_id, None)
                for sid in sub_ids:
                    self.engine.abort(sid)
                raise
            if any(r[2] == "shed" for r in results):
                # the request never produced a token, so a clean 429 +
                # Retry-After is still an honest answer (any non-shed
                # siblings are aborted — the request sheds whole)
                return _shed_whole_request()
            choices, lasts = [], []
            for i, full, finish_reason, last, tok_ids, lp_entries in results:
                lasts.append(last)
                lp_obj = None
                if lp_count is not None:
                    if chat:
                        lp_obj = {"content": _chat_lp_content(
                            self.engine.tokenizer, tok_ids, lp_entries)}
                    else:
                        lp_obj, _ = _completion_lp(
                            self.engine.tokenizer, tok_ids, lp_entries, 0)
                if chat:
                    message = {"role": "assistant", "content": full}
                    if tool_style is not None:
                        from production_stack_tpu.engine.tool_parser import parse_tool_calls

                        content, tool_calls = parse_tool_calls(full, tool_style)
                        if tool_calls:
                            message = {
                                "role": "assistant",
                                "content": content or None,
                                "tool_calls": tool_calls,
                            }
                            if finish_reason == "stop":
                                finish_reason = "tool_calls"
                    choices.append({
                        "index": i,
                        "message": message,
                        "logprobs": lp_obj,
                        "finish_reason": finish_reason,
                    })
                else:
                    choices.append({"index": i, "text": full, "logprobs": lp_obj,
                                    "finish_reason": finish_reason})
            usage = _usage(lasts[0]) if lasts[0] else {}
            if usage and len(lasts) > 1:
                # prompt counted once; completion tokens summed over choices
                usage["completion_tokens"] = sum(
                    (_usage(l) or {}).get("completion_tokens", 0) for l in lasts if l
                )
                usage["total_tokens"] = usage["prompt_tokens"] + usage["completion_tokens"]
            if t_first_box[0] is not None:
                _ttft_hist.observe(t_first_box[0] - t_accept)
            _latency_hist.observe(time.perf_counter() - t_accept)
            _collector.record(
                "engine.request", trace_ctx, t_accept_wall,
                time.perf_counter() - t_accept,
                request_id=req_id, model=model, stream=False, n=n,
            )
            self._live_requests.pop(req_id, None)
            return web.json_response(
                {
                    "id": oid,
                    "object": kind,
                    "created": created,
                    "model": model,
                    "choices": choices,
                    "usage": usage,
                },
                headers={"X-Request-Id": req_id},
            )

        merged = _tag_stream(0, gen) if n == 1 else _merge_streams(gens)
        # queue-deadline shedding: when the engine may still shed queued
        # requests, defer the response headers until the first engine output
        # arrives — a shed then converts to a clean 429 + Retry-After, where
        # committed 200 headers would force the error into the SSE stream.
        # Engines that cannot shed queued work keep the immediate-headers
        # behavior unchanged.
        first_item = None
        if getattr(self.engine, "can_shed_queued", lambda: False)():
            try:
                first_item = await merged.__anext__()
            except StopAsyncIteration:
                first_item = None
            except (Exception, asyncio.CancelledError):
                self._live_requests.pop(req_id, None)
                for sid in sub_ids:
                    self.engine.abort(sid)
                raise
            if (
                first_item is not None
                and first_item[1].finished
                and first_item[1].finish_reason == "shed"
            ):
                await merged.aclose()  # cancel _merge_streams pump tasks now
                return _shed_whole_request()

        async def _chain_first(first, agen):
            if first is not None:
                yield first
            async for item in agen:
                yield item

        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Request-Id": req_id,
            },
        )
        await resp.prepare(request)

        async def send(obj: dict):
            await resp.write(f"data: {json.dumps(obj)}\n\n".encode())

        # chat role chunks are sent lazily with each choice's FIRST engine
        # output (not at request accept): the first streamed bytes must not
        # precede prefill completion, or client-measured TTFT would be ~0
        role_sent = [not chat] * n
        lasts: list = [None] * n
        parsers = tool_idx = None
        if chat and tool_style is not None:
            from production_stack_tpu.engine.tool_parser import StreamingToolParser

            parsers = [StreamingToolParser(tool_style) for _ in range(n)]
            tool_idx = [0] * n
        migrated_away = False
        try:
            lp_offsets = [0] * n
            t_first_out = None
            hop_done = False
            async for i, out in _chain_first(first_item, merged):
                lasts[i] = out
                if (
                    out.finished
                    and out.finish_reason == "migrated"
                    and (mi := self._migrated_out.pop(req_id, None)) is not None
                ):
                    # live migration handoff (docs/migration.md): the
                    # continuation now decodes on the target engine. Emit
                    # the control event the router's splice watches for and
                    # end this leg WITHOUT [DONE] — the router (or an
                    # engine-direct client) attaches to the target's
                    # /migrate_attach for the rest of the stream.
                    await send({"pstpu_migration": mi})
                    migrated_away = True
                    break
                if i == 0 and t_first_out is None:
                    t_first_out = time.perf_counter()
                if not role_sent[i]:
                    role_sent[i] = True
                    await send(
                        {
                            "id": oid, "object": "chat.completion.chunk",
                            "created": created, "model": model,
                            "choices": [{"index": i, "delta": {"role": "assistant"},
                                         "finish_reason": None}],
                        }
                    )
                # emit EVERY engine output (vLLM streams a chunk per step even
                # when the incremental detokenizer held text back as an
                # incomplete UTF-8 sequence): the first chunk is what clients
                # measure TTFT against, and it must track prefill completion,
                # not the first printable character
                lp_obj = None
                if lp_count is not None and out.logprobs is not None:
                    if chat:
                        lp_obj = {"content": _chat_lp_content(
                            self.engine.tokenizer, out.token_ids, out.logprobs)}
                    else:
                        lp_obj, lp_offsets[i] = _completion_lp(
                            self.engine.tokenizer, out.token_ids,
                            out.logprobs, lp_offsets[i])
                if chat:
                    finish_reason = out.finish_reason
                    if parsers is None:
                        deltas = [{"content": out.text_delta} if out.text_delta else {}]
                    else:
                        # split the raw delta into content vs tool-call events;
                        # candidate tool-call text is withheld until it either
                        # completes (a tool_calls delta) or fails to parse at
                        # end-of-stream (flushed back as content)
                        p = parsers[i]
                        events = p.push(out.text_delta or "")
                        if out.finished:
                            events.extend(p.finish())
                            if p.tool_calls and finish_reason == "stop":
                                finish_reason = "tool_calls"
                        deltas = []
                        for ev in events:
                            if ev[0] == "content" and ev[1]:
                                deltas.append({"content": ev[1]})
                            elif ev[0] == "call":
                                deltas.append(
                                    {"tool_calls": [{"index": tool_idx[i], **ev[1]}]}
                                )
                                tool_idx[i] += 1
                        # always emit at least one chunk per engine output:
                        # the first chunk is the client's TTFT signal
                        deltas = deltas or [{}]
                    for j, d in enumerate(deltas):
                        last_d = j == len(deltas) - 1
                        choice = {
                            "index": i,
                            "delta": d,
                            "logprobs": lp_obj if last_d else None,
                            "finish_reason": finish_reason if last_d else None,
                        }
                        await send(
                            {
                                "id": oid, "object": "chat.completion.chunk",
                                "created": created, "model": model, "choices": [choice],
                            }
                        )
                else:
                    await send(
                        {
                            "id": oid, "object": "text_completion", "created": created,
                            "model": model,
                            "choices": [
                                {
                                    "index": i, "text": out.text_delta,
                                    "logprobs": lp_obj,
                                    "finish_reason": out.finish_reason,
                                }
                            ],
                        }
                    )
                if i == 0 and t_first_out is not None and not hop_done:
                    hop_done = True
                    _ttft_hops.append((
                        (t_submit - t_accept) * 1000,
                        (t_first_out - t_submit) * 1000,
                        (time.perf_counter() - t_first_out) * 1000,
                    ))
                    _ttft_hist.observe(t_first_out - t_accept)
            if lasts[0] is not None and not migrated_away:
                usage = _usage(lasts[0])
                if n > 1:
                    usage["completion_tokens"] = sum(
                        (_usage(l) or {}).get("completion_tokens", 0) for l in lasts if l
                    )
                    usage["total_tokens"] = usage["prompt_tokens"] + usage["completion_tokens"]
                await send(
                    {
                        "id": oid, "object": f"{kind}.chunk" if chat else kind,
                        "created": created, "model": model, "choices": [],
                        "usage": usage,
                    }
                )
            if not migrated_away:
                await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            self._live_requests.pop(req_id, None)
            self._migrated_out.pop(req_id, None)
            for sid in sub_ids:
                self.engine.abort(sid)
            raise
        self._live_requests.pop(req_id, None)
        self._migrated_out.pop(req_id, None)
        _latency_hist.observe(time.perf_counter() - t_accept)
        _collector.record(
            "engine.request", trace_ctx, t_accept_wall,
            time.perf_counter() - t_accept,
            request_id=req_id, model=model, stream=True, n=n,
        )
        await resp.write_eof()
        return resp

    def _check_pooling_model(self, body: dict):
        """404/400 for unknown or adapter model names on the pooling endpoints
        (embeddings run the base weights only)."""
        model = body.get("model", self.cfg.name)
        if model == self.cfg.name:
            return None
        if self.engine.lora is not None and self.engine.lora.is_adapter(model):
            return web.json_response(
                {"error": {"message": f"model {model!r} is a LoRA adapter; "
                                      "pooling endpoints serve the base model"}},
                status=400,
            )
        return web.json_response(
            {"error": {"message": f"model {model!r} does not exist",
                       "type": "NotFoundError", "code": 404}},
            status=404,
        )

    def _tokenize_inputs(self, raw) -> list[list[int]]:
        """OpenAI `input` field: str | [str] | [int] | [[int]] -> token lists."""
        if isinstance(raw, str):
            raw = [raw]
        if not isinstance(raw, list):
            raise ValueError("'input' must be a string or a list")
        if raw and isinstance(raw[0], int):
            raw = [raw]
        out = []
        for item in raw:
            if isinstance(item, str):
                out.append(self.engine.tokenizer.encode(item))
            elif isinstance(item, list):
                out.append([int(t) for t in item])
            else:
                raise ValueError(
                    "'input' items must be strings or token-id lists"
                )
        return out

    async def embeddings(self, request: web.Request) -> web.Response:
        """OpenAI-compatible /v1/embeddings: mean-pooled, L2-normalized last
        hidden states (surface parity with the router passthrough endpoint,
        routers/main_router.py in /root/reference)."""
        if self.draining:
            return web.json_response(
                {"error": {"message": "engine is draining for shutdown"}},
                status=503,
            )
        try:
            body = await request.json()
            inputs = self._tokenize_inputs(body.get("input", []))
        except (ValueError, TypeError) as e:
            return web.json_response({"error": {"message": f"invalid request: {e}"}}, status=400)
        err = self._check_pooling_model(body)
        if err is not None:
            return err
        if not inputs:
            return web.json_response({"error": {"message": "'input' is required"}}, status=400)
        try:
            vecs = await self.engine.embed(inputs)
        except (ValueError, RuntimeError) as e:
            return web.json_response({"error": {"message": str(e)}}, status=400)
        total = sum(len(i) for i in inputs)
        return web.json_response(
            {
                "object": "list",
                "model": body.get("model", self.cfg.name),
                "data": [
                    {"object": "embedding", "index": i, "embedding": v.tolist()}
                    for i, v in enumerate(vecs)
                ],
                "usage": {"prompt_tokens": total, "total_tokens": total},
            }
        )

    async def rerank(self, request: web.Request) -> web.Response:
        """/v1/rerank: order documents by cosine relevance to the query."""
        if self.draining:
            return web.json_response(
                {"error": {"message": "engine is draining for shutdown"}},
                status=503,
            )
        try:
            body = await request.json()
            query = body["query"]
            documents = list(body["documents"])
            top_n = max(0, int(body.get("top_n", len(documents))))
        except (KeyError, ValueError, TypeError) as e:
            return web.json_response(
                {"error": {"message": f"invalid request (need query, documents): {e}"}},
                status=400,
            )
        err = self._check_pooling_model(body)
        if err is not None:
            return err
        if not documents:
            return web.json_response({"error": {"message": "'documents' is empty"}}, status=400)
        try:
            vecs = await self.engine.embed(self._tokenize_inputs([query] + documents))
        except (ValueError, RuntimeError) as e:
            return web.json_response({"error": {"message": str(e)}}, status=400)
        scores = vecs[1:] @ vecs[0]
        order = sorted(range(len(documents)), key=lambda i: -float(scores[i]))[:top_n]
        return web.json_response(
            {
                "id": f"rerank-{uuid.uuid4().hex[:16]}",
                "model": body.get("model", self.cfg.name),
                "results": [
                    {
                        "index": i,
                        "document": {"text": documents[i]},
                        "relevance_score": float(scores[i]),
                    }
                    for i in order
                ],
            }
        )

    async def score(self, request: web.Request) -> web.Response:
        """/v1/score: cosine similarity for (text_1, text_2) pairs."""
        if self.draining:
            return web.json_response(
                {"error": {"message": "engine is draining for shutdown"}},
                status=503,
            )
        try:
            body = await request.json()
            t1, t2 = body["text_1"], body["text_2"]
        except (KeyError, ValueError, TypeError) as e:
            return web.json_response(
                {"error": {"message": f"invalid request (need text_1, text_2): {e}"}},
                status=400,
            )
        err = self._check_pooling_model(body)
        if err is not None:
            return err
        def as_items(x):
            """str -> [str]; [int,...] -> [[int,...]]; [str|list,...] -> itself."""
            if isinstance(x, str):
                return [x]
            if isinstance(x, list) and x and isinstance(x[0], int):
                return [x]
            if isinstance(x, list):
                return x
            raise TypeError("text fields must be strings or token-id lists")

        try:
            left = as_items(t1)
            right = as_items(t2)
        except TypeError as e:
            return web.json_response({"error": {"message": str(e)}}, status=400)
        if len(left) == 1:
            left = left * len(right)
        if len(left) != len(right):
            return web.json_response(
                {"error": {"message": "text_1 and text_2 lengths do not match"}},
                status=400,
            )
        try:
            inputs = self._tokenize_inputs(left + right)
            vecs = await self.engine.embed(inputs)
        except (ValueError, RuntimeError) as e:
            return web.json_response({"error": {"message": str(e)}}, status=400)
        n = len(left)
        return web.json_response(
            {
                "id": f"score-{uuid.uuid4().hex[:16]}",
                "object": "list",
                "model": body.get("model", self.cfg.name),
                "data": [
                    {"index": i, "object": "score",
                     "score": float(vecs[i] @ vecs[n + i])}
                    for i in range(n)
                ],
                "usage": {"prompt_tokens": sum(len(i) for i in inputs)},
            }
        )

    async def sleep(self, request: web.Request) -> web.Response:
        if not self.cfg.enable_sleep_mode:
            return web.json_response({"error": "sleep mode disabled"}, status=400)
        try:
            level = int(request.query.get("level", "1"))
            # executor: sleep waits for the device thread (an in-flight step
            # must drain first) — the event loop must keep serving probes
            await asyncio.get_running_loop().run_in_executor(
                None, self.engine.sleep, level
            )
        except ValueError as e:  # bad level param
            return web.json_response({"error": str(e)}, status=400)
        return web.Response(text="")

    async def wake_up(self, request: web.Request) -> web.Response:
        if not self.cfg.enable_sleep_mode:
            return web.json_response({"error": "sleep mode disabled"}, status=400)
        await asyncio.get_running_loop().run_in_executor(
            None, self.engine.wake_up
        )
        return web.Response(text="")

    async def is_sleeping(self, request: web.Request) -> web.Response:
        return web.json_response({"is_sleeping": self.engine.is_sleeping})

    async def load_lora_adapter(self, request: web.Request) -> web.Response:
        """Contract parity: the reference LoraAdapter controller POSTs
        {lora_name, lora_path} here (loraadapter_controller.go:586-601)."""
        body = await request.json()
        name, path = body.get("lora_name"), body.get("lora_path")
        if not name or not path:
            return web.json_response(
                {"error": "lora_name and lora_path are required"}, status=400
            )
        try:
            slot = await asyncio.get_running_loop().run_in_executor(
                None, self.engine.load_lora_adapter, name, path
            )
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"status": "success", "lora_name": name, "slot": slot})

    async def unload_lora_adapter(self, request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        if not name:
            return web.json_response({"error": "lora_name is required"}, status=400)
        try:
            await asyncio.get_running_loop().run_in_executor(
                None, self.engine.unload_lora_adapter, name
            )
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"status": "success", "lora_name": name})

    # -- app ---------------------------------------------------------------

    def build_app(self) -> web.Application:
        # client_max_size: aiohttp's 1 MiB default would reject /migrate_in
        # snapshots for long-context sequences (a 128k-token stream's token
        # list alone is ~1 MB) — exactly the long streams migration exists
        # to protect. 64 MiB bounds a ~1M-token snapshot.
        app = web.Application(client_max_size=64 << 20)
        r = app.router
        r.add_get("/health", self.health)
        r.add_get("/ping", self.health)
        r.add_get("/version", self.version)
        r.add_get("/v1/models", self.models)
        r.add_get("/metrics", self.metrics)
        r.add_get("/stats", self.stats)
        # SLO terminal records: an intra-cluster read-only surface like
        # /stats (the router's scraper consumes it in production, so it is
        # NOT debug-gated; it carries request ids and timings, no content)
        r.add_get("/slo_records", self.slo_records)
        if self.cfg.enable_debug_endpoints:
            # unauthenticated debug surfaces — benchmark/debug runs only.
            # /v1/traces is read-only but exposes request ids and timings;
            # wiping the hop-quantile sample windows (/metrics/reset)
            # corrupts live observability, so production servers register
            # neither. The flight recorder additionally exposes scheduler
            # internals, so it rides the same gate.
            r.add_get("/v1/traces", self.traces)
            r.add_get("/v1/debug/flightrecorder", self.flightrecorder)
            r.add_post("/metrics/reset", self.metrics_reset)
        r.add_post("/abort", self.abort)
        # live sequence migration (docs/migration.md): registered even when
        # --no-migration (handlers answer 501) so the wire surface — and the
        # GC005 fake-engine parity contract — stays stable
        r.add_get("/migratable", self.migratable)
        # KV fabric discovery (docs/kv-fabric.md): peers resolve this
        # engine's fabric listener here (--kv-fabric-port 0 binds an
        # ephemeral port, so config alone cannot name it). Registered even
        # when the fabric is off (answers enabled:false) so the surface —
        # and the fake-engine parity contract — stays stable.
        r.add_get("/kv_fabric", self.kv_fabric_info)
        r.add_post("/migrate_out", self.migrate_out)
        r.add_post("/migrate_in", self.migrate_in)
        r.add_post("/migrate_attach", self.migrate_attach)
        r.add_post("/tokenize", self.tokenize)
        r.add_post("/detokenize", self.detokenize)
        r.add_post("/v1/chat/completions", self.chat_completions)
        r.add_post("/v1/completions", self.completions)
        r.add_post("/v1/embeddings", self.embeddings)
        r.add_post("/v1/rerank", self.rerank)
        r.add_post("/v2/rerank", self.rerank)
        r.add_post("/v1/score", self.score)
        r.add_post("/sleep", self.sleep)
        r.add_post("/wake_up", self.wake_up)
        r.add_get("/is_sleeping", self.is_sleeping)
        r.add_post("/v1/load_lora_adapter", self.load_lora_adapter)
        r.add_post("/v1/unload_lora_adapter", self.unload_lora_adapter)
        app.on_cleanup.append(self._close_mig_client)
        return app


def _resolve_process_id(cfg: EngineConfig) -> int:
    """Process id for multi-host serving: explicit flag, else JAX_PROCESS_ID,
    else the StatefulSet hostname ordinal (``engine-llama3-2`` -> 2)."""
    import os
    import socket as socket_mod

    if cfg.distributed_process_id is not None:
        return int(cfg.distributed_process_id)
    if os.environ.get("JAX_PROCESS_ID"):
        return int(os.environ["JAX_PROCESS_ID"])
    host = socket_mod.gethostname()
    tail = host.rsplit("-", 1)[-1]
    if not tail.isdigit():
        raise ValueError(
            f"cannot derive process id from hostname {host!r}; set "
            "--distributed-process-id or JAX_PROCESS_ID"
        )
    return int(tail)


def _init_multihost(cfg: EngineConfig) -> int:
    """Rendezvous the JAX multi-controller runtime (the reference's Ray
    cluster + EXPECTED_NODES barrier, ray-cluster.yaml:46-47 — replaced by
    jax.distributed's coordination service). Returns this process's id."""
    import jax

    if not cfg.distributed_coordinator:
        raise ValueError(
            "--distributed-num-processes > 1 requires --distributed-coordinator"
        )
    # KV offload tiers work multi-host: get_page is a REPLICATED dispatch
    # that gathers the page fully-replicated (SPMD) so the leader's host
    # fetch sees the whole page; set_page restores broadcast the bytes back.
    # The tiers/controller/cache-server connections are leader-only
    # (followers get them disabled in serve()).
    # sleep mode works multi-host at BOTH levels: drop_kv_pools/reset_kv
    # and offload_params/restore_params are replicated dispatches — each
    # process offloads its own param shards to its own host RAM and
    # re-materializes them on wake.
    # LoRA works multi-host: the leader parses adapter checkpoints and the
    # resulting set_lora_slot/clear_lora_slot device writes are REPLICATED
    # dispatches — followers receive the weights over the step stream, so
    # adapters need no shared filesystem.
    # Disaggregated prefill works multi-host on BOTH paths: the TCP path's
    # page fetches (get_page) and restores (set_page) are REPLICATED SPMD
    # dispatches with the sender/receiver leader-only; the device-to-device
    # path runs a transfer endpoint per process (runner.kv_endpoint_start,
    # armed by engine.enable_multihost_device_kv after the broadcaster is
    # wired) so pages move shard-cluster to shard-cluster over DCN with no
    # host serde — the NIXL GPU-direct analogue.
    pid = _resolve_process_id(cfg)
    logger.info(
        "multi-host init: process %d/%d, coordinator %s",
        pid, cfg.distributed_num_processes, cfg.distributed_coordinator,
    )
    jax.distributed.initialize(
        coordinator_address=cfg.distributed_coordinator,
        num_processes=cfg.distributed_num_processes,
        process_id=pid,
    )
    return pid


async def serve(cfg: EngineConfig, engine: Optional[LLMEngine] = None):
    if cfg.distributed_num_processes > 1 and engine is None:
        from production_stack_tpu.engine.distributed import (
            BroadcastingRunner,
            StepBroadcaster,
            follower_loop,
        )

        pid = _init_multihost(cfg)
        if pid != 0:
            # follower: identical RUNNER construction (same model, mesh,
            # pools, seed), then replay the leader's device dispatches
            # forever. Host-side KV tiers / controller / remote-cache
            # connections are leader-only — a follower building them would
            # double-register with the KV index controller and waste host
            # RAM on a tier nothing reads. This call BLOCKS until the
            # leader shuts down.
            import dataclasses as _dc

            engine = LLMEngine(_dc.replace(
                cfg, kv_offload_cpu_gb=0.0, kv_offload_dir=None,
                kv_remote_url=None, kv_controller_url=None,
                kv_role="none",
            ))
            leader_host = cfg.distributed_coordinator.rsplit(":", 1)[0]
            await asyncio.get_event_loop().run_in_executor(
                None,
                follower_loop,
                engine.runner,
                leader_host,
                cfg.worker_sync_port,
            )
            raise SystemExit(0)
        engine = LLMEngine(cfg)
        bc = StepBroadcaster(
            cfg.worker_sync_port, cfg.distributed_num_processes - 1
        )
        engine.runner = BroadcastingRunner(engine.runner, bc)
        if engine.lora is not None:
            # LoRAManager captured the raw runner at engine construction;
            # re-point it at the wrapper or set_lora_slot/clear_lora_slot
            # would bypass replication and followers would keep zero slots
            engine.lora.runner = engine.runner
        if engine._offload is not None:
            # same capture pattern: the offload connector's get_page/set_page
            # must go through the broadcaster or followers desync on the
            # SPMD page-gather program
            engine._offload.runner = engine.runner
        if cfg.kv_role != "none" and cfg.kv_transfer_device:
            # device-to-device KV across hosts: per-process endpoints +
            # replicated offer/pull/restore dispatches (must come after the
            # BroadcastingRunner wrap so followers mirror every step)
            engine.enable_multihost_device_kv()
    from production_stack_tpu.tracing import configure_tracing

    configure_tracing(
        sample_rate=cfg.trace_sample_rate, capacity=cfg.trace_buffer_size
    )
    server = EngineServer(cfg, engine)
    server.engine.start()
    app = server.build_app()
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, cfg.host, cfg.port)
    await site.start()
    logger.info("engine API listening on %s:%d (model=%s)", cfg.host, cfg.port, cfg.name)
    return server, runner


def main():
    import os as os_mod

    from production_stack_tpu.utils.signals import wait_for_termination

    p = argparse.ArgumentParser("tpu-engine")
    add_engine_args(p)
    args = p.parse_args()
    cfg = config_from_args(args)

    async def _run():
        server, runner = await serve(cfg)
        await wait_for_termination()
        # K8s pod rotation: SIGTERM -> refuse new work + flip /health to 503
        # (readiness pulls the pod from rotation) -> let in-flight requests
        # finish -> clean shutdown, all inside terminationGracePeriodSeconds
        await server.drain(float(os_mod.environ.get("PSTPU_DRAIN_TIMEOUT", "30")))
        try:
            await asyncio.wait_for(runner.cleanup(), 15)
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass
        server.engine.stop()
        logger.info("engine shut down cleanly")

    asyncio.run(_run())


if __name__ == "__main__":
    main()
