"""TPU device telemetry for the engine's ``/metrics`` surface.

The scheduler can only make decisions the telemetry lets it see: ROADMAP
item 3 (saturation-driven autoscaling) and item 4 (on-chip prefill retuning)
both need continuously-exported device state — HBM pressure, KV-pool
occupancy against the remaining headroom, compile activity, and how much of
wall time the engine loop actually spends inside device programs. This module
samples all of it lazily on scrape (no background thread, no work between
scrapes) and renders Prometheus exposition lines the engine API server
appends to ``/metrics``.

Exported series (docs/observability.md has the reference table):

- ``vllm:tpu_hbm_bytes_in_use{device=...}`` / ``vllm:tpu_hbm_bytes_limit``
  — per-device memory via ``jax.local_devices()[i].memory_stats()``. On
  backends without device memory stats (CPU tests, some interpret modes)
  the sampler degrades to one ``device="host"`` row backed by process RSS /
  total host RAM, so dashboards keep a live series instead of a hole.
- ``vllm:hbm_headroom_bytes`` — sum(limit) - sum(in_use): what is left for
  KV growth, staging buffers, and compile workspaces.
- ``vllm:kv_pool_device_bytes`` / ``vllm:kv_pool_used_bytes`` — the paged KV
  pool's device footprint and its in-use share (occupancy x footprint), the
  pair the "HBM headroom" dashboard panel charts against headroom.
- ``vllm:compile_seconds_total`` / ``vllm:compile_events_total`` — cumulative
  XLA backend-compile wall time, hooked via ``jax.monitoring`` (the same
  listener feeds the flight recorder's ``compile`` events): a serving pod
  spending minutes here mid-traffic is retracing, which is exactly the
  regression the shape-bucketing scheduler exists to prevent.
- ``vllm:compile_cache_entries`` / ``vllm:compile_cache_bytes`` — persistent
  compilation-cache size on disk (utils/compile_cache.py), sampled at most
  every 30 s.
- ``vllm:engine_step_duty_cycle`` — fraction of wall time the engine loop
  spent inside device dispatches since the previous scrape (delta of
  ``loop_seconds["step"]`` over delta wall): ~1.0 means the device is the
  bottleneck, ~0.0 under load means the host side is.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

# -- JAX compile listener -----------------------------------------------------
#
# jax.monitoring fires '/jax/core/compile/backend_compile_duration' once per
# XLA backend compile. One process-global listener accumulates the totals and
# mirrors each event into the flight recorder, so a compile stall shows up in
# an anomaly dump next to the scheduler events it starved.

_compile_lock = threading.Lock()
_compile_seconds_total = 0.0
_compile_events_total = 0
_listener_installed = False

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_event_duration(name: str, duration: float, **_kw) -> None:
    global _compile_seconds_total, _compile_events_total
    if name != _COMPILE_EVENT:
        return
    with _compile_lock:
        _compile_seconds_total += duration
        _compile_events_total += 1
    try:
        from production_stack_tpu.tracing import get_flightrecorder

        get_flightrecorder().record(
            "compile", event="backend_compile", seconds=round(duration, 4)
        )
    except Exception:  # noqa: BLE001 - telemetry must never break a compile
        pass


def install_compile_listener() -> bool:
    """Register the jax.monitoring duration listener once per process.
    Idempotent; returns whether the listener is active (False when JAX's
    monitoring API is unavailable — telemetry then reports zeros)."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        import jax.monitoring as monitoring

        monitoring.register_event_duration_secs_listener(_on_event_duration)
    except Exception as e:  # noqa: BLE001 - monitoring API may be absent
        logger.warning("jax compile telemetry unavailable (%s)", e)
        return False
    _listener_installed = True
    return True


def compile_totals() -> tuple[float, int]:
    with _compile_lock:
        return _compile_seconds_total, _compile_events_total


class DeviceMonitor:
    """Lazy on-scrape sampler. Holds a reference to the engine (duck-typed:
    fake/test engines without a KV manager or loop_seconds degrade to the
    host-memory row and zero KV gauges) and caches device samples briefly so
    a scrape storm cannot turn telemetry into load."""

    SAMPLE_MAX_AGE_S = 1.0
    CACHE_SCAN_MAX_AGE_S = 30.0

    def __init__(self, engine=None):
        self.engine = engine
        self._mem_sample: tuple[float, list] = (0.0, [])
        self._cache_sample: tuple[float, int, int] = (0.0, 0, 0)
        self._cache_scanning = False
        self._duty_prev: Optional[tuple[float, float]] = None

    # -- device memory ------------------------------------------------------

    def _device_memory(self) -> list[dict]:
        """[{device, bytes_in_use, bytes_limit}] — per accelerator when the
        backend exposes memory_stats, else one host-memory fallback row."""
        now = time.monotonic()
        ts, cached = self._mem_sample
        if cached and now - ts < self.SAMPLE_MAX_AGE_S:
            return cached
        rows: list[dict] = []
        try:
            import jax

            for d in jax.local_devices():
                stats = None
                try:
                    stats = d.memory_stats()
                except Exception:  # noqa: BLE001 - backend-dependent API
                    stats = None
                if not stats:
                    continue
                rows.append({
                    "device": f"{d.platform}:{d.id}",
                    "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                    "bytes_limit": int(
                        stats.get("bytes_limit")
                        or stats.get("bytes_reservable_limit")
                        or 0
                    ),
                })
        except Exception:  # noqa: BLE001 - no jax / no devices: host fallback
            rows = []
        if not rows:
            rows = [self._host_memory_row()]
        self._mem_sample = (now, rows)
        return rows

    @staticmethod
    def _host_memory_row() -> dict:
        """CPU fallback: the process's RSS against total host RAM. Not HBM,
        but it keeps the dashboard series alive and the headroom math sane
        on CPU test rigs."""
        try:
            import psutil

            vm = psutil.virtual_memory()
            return {
                "device": "host",
                "bytes_in_use": int(psutil.Process().memory_info().rss),
                "bytes_limit": int(vm.total),
            }
        except Exception:  # noqa: BLE001 - psutil missing: zero row
            return {"device": "host", "bytes_in_use": 0, "bytes_limit": 0}

    def _pool_shards(self) -> "list[tuple[str, int]]":
        """Per-mesh-device KV pool footprint from the runner's static pool
        sharding (engine/runner.py kv_pool_shard_layout) — live buffers are
        donated every step and must not be introspected from the scrape
        thread. Fake/test engines without a runner degrade to no rows."""
        runner = getattr(self.engine, "runner", None)
        layout = getattr(runner, "kv_pool_shard_layout", None)
        if layout is None:
            return []
        try:
            return list(layout())
        except Exception:  # noqa: BLE001 - telemetry must never break a scrape
            return []

    # -- compile cache ------------------------------------------------------

    def _compile_cache_size(self) -> tuple[int, int]:
        """(entries, bytes) of the persistent XLA cache directory. The walk
        can touch thousands of files, and /metrics is served on the aiohttp
        event loop — so the scrape always returns the CACHED value and, when
        it is older than CACHE_SCAN_MAX_AGE_S, kicks a background refresh
        (first scrape reports zeros until the first walk lands)."""
        now = time.monotonic()
        ts, entries, size = self._cache_sample
        if (
            now - ts >= self.CACHE_SCAN_MAX_AGE_S or ts == 0.0
        ) and not self._cache_scanning:
            self._cache_scanning = True
            threading.Thread(target=self._scan_compile_cache, daemon=True).start()
        return entries, size

    def _scan_compile_cache(self) -> None:
        entries = size = 0
        try:
            from production_stack_tpu.utils import compile_cache

            root = compile_cache._enabled_dir
            if root and os.path.isdir(root):
                for dirpath, _dirs, files in os.walk(root):
                    for name in files:
                        try:
                            size += os.path.getsize(os.path.join(dirpath, name))
                            entries += 1
                        except OSError:
                            continue
        except Exception:  # noqa: BLE001 - cache dir races are harmless
            pass
        self._cache_sample = (time.monotonic(), entries, size)
        self._cache_scanning = False

    # -- duty cycle ---------------------------------------------------------

    def _duty_cycle(self) -> float:
        """d(step seconds)/d(wall) since the previous scrape; 0.0 when the
        engine does not account loop sections (fakes) or on the first
        scrape."""
        loop_seconds = getattr(self.engine, "loop_seconds", None)
        if not isinstance(loop_seconds, dict):
            return 0.0
        now = time.monotonic()
        step = float(loop_seconds.get("step", 0.0))
        prev = self._duty_prev
        self._duty_prev = (now, step)
        if prev is None or now - prev[0] <= 0:
            return 0.0
        return min(1.0, max(0.0, (step - prev[1]) / (now - prev[0])))

    # -- exposition ---------------------------------------------------------

    def metrics_lines(self, model: str) -> list[str]:
        labels = f'model_name="{model}"'
        lines = [
            "# TYPE vllm:tpu_hbm_bytes_in_use gauge",
            "# TYPE vllm:tpu_hbm_bytes_limit gauge",
        ]
        total_use = total_limit = 0
        for row in self._device_memory():
            dl = f'{labels},device="{row["device"]}"'
            lines.append(f"vllm:tpu_hbm_bytes_in_use{{{dl}}} {row['bytes_in_use']}")
            lines.append(f"vllm:tpu_hbm_bytes_limit{{{dl}}} {row['bytes_limit']}")
            total_use += row["bytes_in_use"]
            total_limit += row["bytes_limit"]
        lines += [
            "# TYPE vllm:hbm_headroom_bytes gauge",
            f"vllm:hbm_headroom_bytes{{{labels}}} {max(0, total_limit - total_use)}",
        ]
        kv = getattr(self.engine, "kv", None)
        page_bytes = int(getattr(self.engine, "kv_page_bytes", 0) or 0)
        if kv is not None and page_bytes:
            pool_bytes = kv.num_pages * page_bytes
            used = int(pool_bytes * kv.usage())
            lines += [
                "# TYPE vllm:kv_pool_device_bytes gauge",
                f"vllm:kv_pool_device_bytes{{{labels}}} {pool_bytes}",
                "# TYPE vllm:kv_pool_used_bytes gauge",
                f"vllm:kv_pool_used_bytes{{{labels}}} {used}",
            ]
            # per-mesh-device pool footprint: under tensor parallelism each
            # chip holds its kv-head shard of every page, so the per-shard
            # series (≈ pool/tp each) is what the per-shard HBM-headroom
            # panel charts — a device-0-only row would claim N× the real
            # per-chip load (docs/multichip-serving.md)
            shards = self._pool_shards()
            if shards:
                lines.append("# TYPE vllm:kv_pool_shard_bytes gauge")
                for dev, nbytes in shards:
                    dl = f'{labels},device="{dev}"'
                    lines.append(
                        f"vllm:kv_pool_shard_bytes{{{dl}}} {nbytes}"
                    )
        secs, events = compile_totals()
        entries, cache_bytes = self._compile_cache_size()
        lines += [
            "# TYPE vllm:compile_seconds_total counter",
            f"vllm:compile_seconds_total{{{labels}}} {round(secs, 4)}",
            "# TYPE vllm:compile_events_total counter",
            f"vllm:compile_events_total{{{labels}}} {events}",
            "# TYPE vllm:compile_cache_entries gauge",
            f"vllm:compile_cache_entries{{{labels}}} {entries}",
            "# TYPE vllm:compile_cache_bytes gauge",
            f"vllm:compile_cache_bytes{{{labels}}} {cache_bytes}",
            "# TYPE vllm:engine_step_duty_cycle gauge",
            f"vllm:engine_step_duty_cycle{{{labels}}} "
            f"{round(self._duty_cycle(), 4)}",
        ]
        return lines
