"""Tokenizer abstraction.

Serving pods load a HuggingFace tokenizer from the model directory (mounted
PVC — the reference caches weights on a PVC the same way, tutorials/03). In
hermetic environments (tests, random-weight benchmarks) a built-in byte-level
tokenizer is used so the whole stack runs with zero downloads.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)


def _generic_chat_template(messages: list[dict], tools: Optional[list] = None) -> str:
    """Dependency-free fallback template. Renders tool schemas into a system
    preamble (hermes-tag convention, matched by engine/tool_parser.py) and
    serializes assistant tool_calls / tool-result turns so multi-step tool
    conversations round-trip."""
    parts = []
    if tools:
        schemas = "\n".join(
            json.dumps(t.get("function", t), sort_keys=True) for t in tools
        )
        parts.append(
            "<|system|>\nYou may call functions. Available tools:\n"
            f"{schemas}\n"
            "To call one, reply with "
            '<tool_call>{"name": <name>, "arguments": {...}}</tool_call>\n'
        )
    for m in messages:
        role = m.get("role", "user")
        content = m.get("content") or ""
        if m.get("tool_calls"):
            calls = []
            for c in m["tool_calls"]:
                if c.get("type", "function") != "function":
                    continue
                raw = c["function"].get("arguments") or "{}"
                try:
                    args = json.loads(raw)
                except ValueError:
                    args = raw  # pass malformed arguments through verbatim
                calls.append(
                    "<tool_call>"
                    + json.dumps(
                        {"name": c["function"]["name"], "arguments": args},
                        sort_keys=True,
                    )
                    + "</tool_call>"
                )
            content = f"{content}{''.join(calls)}"
        parts.append(f"<|{role}|>\n{content}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


class ByteTokenizer:
    """Reversible byte-level tokenizer: token = byte value; specials above 255."""

    bos_token_id = 256
    eos_token_id = 257
    pad_token_id = 258
    vocab_size = 512

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8", errors="replace"))
        return ([self.bos_token_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(
        self, messages: list[dict], tools: Optional[list] = None
    ) -> str:
        return _generic_chat_template(messages, tools)


class HFTokenizer:
    """Wrapper over a local HuggingFace tokenizer directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id
        self.pad_token_id = self._tok.pad_token_id or self._tok.eos_token_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(
        self, messages: list[dict], tools: Optional[list] = None
    ) -> str:
        try:
            kw = {"tools": tools} if tools else {}
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True, **kw
            )
        except Exception as e:
            # a malformed template (or a tools-rendering bug) must not
            # degrade output silently
            logger.warning(
                "HF chat template failed (%s: %s); falling back to the "
                "generic <|role|> template", type(e).__name__, e,
            )
            return _generic_chat_template(messages, tools)


def load_tokenizer(model_path: Optional[str]):
    """HF tokenizer if `model_path` holds one locally, else the byte tokenizer."""
    if model_path:
        try:
            return HFTokenizer(model_path)
        except Exception:
            pass
    return ByteTokenizer()
