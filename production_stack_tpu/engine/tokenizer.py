"""Tokenizer abstraction.

Serving pods load a HuggingFace tokenizer from the model directory (mounted
PVC — the reference caches weights on a PVC the same way, tutorials/03). In
hermetic environments (tests, random-weight benchmarks) a built-in byte-level
tokenizer is used so the whole stack runs with zero downloads.
"""

from __future__ import annotations

from typing import Optional, Sequence


class ByteTokenizer:
    """Reversible byte-level tokenizer: token = byte value; specials above 255."""

    bos_token_id = 256
    eos_token_id = 257
    pad_token_id = 258
    vocab_size = 512

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8", errors="replace"))
        return ([self.bos_token_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages: list[dict]) -> str:
        parts = [f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}\n" for m in messages]
        parts.append("<|assistant|>\n")
        return "".join(parts)


class HFTokenizer:
    """Wrapper over a local HuggingFace tokenizer directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id
        self.pad_token_id = self._tok.pad_token_id or self._tok.eos_token_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages: list[dict]) -> str:
        try:
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        except Exception:
            parts = [f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}\n" for m in messages]
            parts.append("<|assistant|>\n")
            return "".join(parts)


def load_tokenizer(model_path: Optional[str]):
    """HF tokenizer if `model_path` holds one locally, else the byte tokenizer."""
    if model_path:
        try:
            return HFTokenizer(model_path)
        except Exception:
            pass
    return ByteTokenizer()
