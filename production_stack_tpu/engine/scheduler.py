"""Continuous-batching scheduler with shape bucketing.

Every jit shape is quantized: decode batches to power-of-two buckets, prefill
chunks to a small set of lengths, page tables to power-of-two widths — so XLA
compiles a bounded set of programs and steady-state serving never retraces
(SURVEY.md §7 hard part #1).

Policy (one device program per step, prefill-prioritized):
- If any admitted sequence still has uncomputed prompt tokens, run one chunked
  prefill step for up to ``prefill_batch`` such sequences (shortest-first to
  release TTFT quickly).
- Otherwise run one decode step over all running sequences.
- Admission: a waiting sequence is admitted when its prompt's non-cached pages
  fit in the allocator (prefix-cache hits make admission cheaper — KV reuse).

The reference gets this behavior from vLLM (continuous batching + chunked
prefill, enabled at helm/templates/deployment-vllm-multi.yaml:128-135); here it
is first-party.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from production_stack_tpu.engine.kv_manager import KVPageManager


@dataclass
class SamplingParams:
    max_tokens: int = 128
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    stop: list[str] = field(default_factory=list)
    ignore_eos: bool = False
    # suppress EOS-driven finishes until this many tokens were generated
    # (vLLM's min_tokens; stop strings and length limits still apply)
    min_tokens: int = 0
    seed: Optional[int] = None
    # top-logprob count to report per token (None = off; device computes a
    # fixed TOP_LOGPROBS wide set, the host slices to this many)
    logprobs: Optional[int] = None
    # OpenAI penalties (0 = off) over generated tokens; vLLM repetition
    # penalty (1 = off) over prompt + generated tokens
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    # OpenAI logit_bias: token id -> additive bias in [-100, 100], applied
    # to the sampling distribution on device (reported logprobs stay raw,
    # matching the penalties convention)
    logit_bias: Optional[dict] = None

    @property
    def wants_penalties(self) -> bool:
        return (
            self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
            or self.repetition_penalty != 1.0
        )


@dataclass
class Sequence:
    seq_id: str
    prompt_ids: list[int]
    params: SamplingParams
    arrival_time: float = field(default_factory=time.monotonic)
    output_ids: list[int] = field(default_factory=list)
    pages: list[int] = field(default_factory=list)
    # high-watermark of pages ever owned (SLO terminal records report it —
    # the request's real KV footprint, which free() at finish erases)
    pages_peak: int = 0
    num_computed: int = 0          # prompt tokens already prefilled (incl. cached)
    num_cached: int = 0            # tokens served from the prefix cache
    finished: bool = False
    finish_reason: Optional[str] = None
    first_token_time: Optional[float] = None
    first_dispatch_time: Optional[float] = None  # admission-wait instrumentation
    lora_slot: int = 0             # adapter slot (0 = base model)
    cache_salt: bytes = b""        # prefix-cache salt (adapter identity)
    # exempt from load shedding (queue bound + queue deadline): set by the
    # API layer for parallel-sampling SIBLINGS (choice > 0), which only
    # launch after choice 0's first output — their request is mid-flight,
    # a 429 is no longer possible, and shedding one choice would leak a
    # zero-token 'shed' finish into a committed stream. Choice 0 itself
    # stays sheddable: its pre-output shed converts the whole request to a
    # clean 429 and the siblings are aborted with it.
    shed_exempt: bool = False
    # SLO class ("interactive" | "batch", docs/failure-handling.md): batch
    # saturates earlier, expires earlier, yields prefill chunk slots, and
    # is preempted first under page pressure — the whole degradation order
    # under overload keys off this field
    priority: str = "interactive"
    # distributed-tracing context (tracing.SpanContext of the engine.request
    # span) — phase spans for this sequence parent under it; None = untraced
    trace: Optional[object] = None
    trace_done: bool = False       # phase spans recorded (guard against dupes)
    finish_time: Optional[float] = None  # monotonic, set by _finish
    # per-request SLO accounting (engine terminal records): inter-emit gaps
    # normalized per token (a burst emit of k tokens contributes gap/k), so
    # the record's itl_p99_ms reflects what a streaming client experienced.
    # Capped — a 32k-token stream must not grow an unbounded list.
    last_emit_time: Optional[float] = None
    itl_samples: list = field(default_factory=list)
    slo_done: bool = False         # terminal record emitted (guard)
    # phase-span contexts, pre-allocated at first admission attempt so
    # offload spill/restore spans triggered inside the scheduler can parent
    # under the phase whose wall window contains them (first admission ->
    # queue; post-preemption re-admission -> prefill or decode). As siblings
    # of the phase they overlap they would double-count in self-time
    # attribution. engine._record_phase_trace records the phase spans under
    # these same contexts at finish.
    queue_span: Optional[object] = None
    prefill_span: Optional[object] = None
    decode_span: Optional[object] = None

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_ids) + len(self.output_ids)

    @property
    def in_prefill(self) -> bool:
        return self.num_computed < len(self.prompt_ids)


@dataclass
class ScheduledBatch:
    kind: str                      # "prefill" | "decode"
    seqs: list[Sequence]
    # padded device inputs
    input_ids: np.ndarray
    positions: np.ndarray
    page_table: np.ndarray
    kv_lens: np.ndarray
    temperature: np.ndarray
    top_k: np.ndarray
    top_p: np.ndarray
    lora_ids: np.ndarray = None    # [B] int32 adapter slot per row
    kv_limits: np.ndarray = None   # [B] int32 KV capacity bound (multi-step)
    history: np.ndarray = None     # [B, H] token ids (speculative drafting)
    # how many tokens of each seq this step computes (prefill chunking)
    chunk_sizes: list[int] = field(default_factory=list)
    # chained decode bursts this dispatch covers (runner.step_multi_pipelined)
    bursts: int = 1
    # any sequence in the batch wants per-token logprobs
    want_logprobs: bool = False
    # any sequence in the batch has sampling penalties; history/prompt_lens
    # are set when true
    want_penalties: bool = False
    prompt_lens: np.ndarray = None  # [B] int32 (penalty batches)


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class Scheduler:
    DECODE_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
    CHUNK_BUCKETS = (16, 32, 64, 128, 256, 512, 1024)
    PAGE_BUCKETS = (2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
    HISTORY_BUCKETS = CHUNK_BUCKETS + (2048, 4096, 8192, 16384, 32768)

    def __init__(
        self,
        kv: KVPageManager,
        *,
        max_num_seqs: int = 64,
        max_model_len: int = 4096,
        prefill_chunk: int = 512,
        prefill_batch: int = 4,
        enable_prefix_caching: bool = True,
        batch_multiple: int = 1,
        decode_steps: int = 1,
        decode_pipeline: int = 1,
        spec_k: int = 0,
        spec_ngram: int = 3,
        max_waiting_seqs: int = 0,
        queue_deadline_s: float = 0.0,
        interactive_reserve: int = 1,
        batch_queue_deadline_s: float = 0.0,
        batch_prefill_share: float = 0.5,
    ):
        self.kv = kv
        self.max_num_seqs = max_num_seqs
        self.max_model_len = max_model_len
        self.prefill_chunk = prefill_chunk
        self.prefill_batch = prefill_batch
        self.enable_prefix_caching = enable_prefix_caching
        # device batch dims must divide evenly over the dp mesh axis: round
        # every batch bucket up to a multiple of this (padded rows are inert —
        # positions -1, zero budgets)
        self.batch_multiple = max(1, batch_multiple)
        # decode burst length: tokens produced per device program (fused
        # multi-step decode, runner.step_multi); 1 = classic per-token steps.
        # With spec_k > 0 it is the number of fused draft+verify ROUNDS instead
        # (runner.step_spec), each emitting 1..spec_k+1 tokens.
        self.decode_steps = max(1, decode_steps)
        # chained bursts per decode dispatch when the batch is quiescent (no
        # waiting work): m bursts cost m*compute + 1 fetch round trip instead
        # of m of each (runner.step_multi_pipelined)
        self.decode_pipeline = max(1, decode_pipeline)
        self.spec_k = max(0, spec_k)
        self.spec_ngram = max(1, spec_ngram)
        # admission control (overload survival, docs/failure-handling.md):
        # a bounded waiting queue — the API layer sheds (429 + Retry-After)
        # once num_waiting() reaches max_waiting_seqs (0 = unbounded) — and
        # a per-request queue deadline: a request still undispatched after
        # queue_deadline_s seconds is shed by the engine loop (0 = never).
        # Unbounded queues turn overload into unbounded TTFT for EVERYONE;
        # shedding keeps the served subset's latency sane and tells clients
        # exactly when to retry.
        self.max_waiting_seqs = max(0, max_waiting_seqs)
        self.queue_deadline_s = max(0.0, queue_deadline_s)
        # SLO classes (docs/failure-handling.md "Priority classes"): the
        # last `interactive_reserve` slots of a bounded waiting queue only
        # admit interactive work, so sustained batch load can never starve
        # interactive out of admission; batch optionally expires on its own
        # (shorter) queue deadline, and its share of a prefill dispatch's
        # chunk slots is capped while interactive prefill work is waiting.
        self.interactive_reserve = max(0, interactive_reserve)
        self.batch_queue_deadline_s = max(0.0, batch_queue_deadline_s)
        self.batch_prefill_share = min(1.0, max(0.0, batch_prefill_share))
        self.waiting: list[Sequence] = []
        self.running: list[Sequence] = []
        self.preemptions_total = 0
        self._last_kind = "decode"  # prefill/decode alternation state
        # adaptive chain-depth inputs, refreshed by the engine loop each
        # iteration: recent request arrivals/sec and the measured per-burst
        # wall time. A chained dispatch delays the next scheduling decision
        # by (bursts-1) * burst_seconds, during which an arrival cannot start
        # its prefill — exactly the TTFT admission-wait tradeoff.
        self.arrival_rate = 0.0
        self.burst_seconds = 0.05
        # seconds since the engine last saw a request arrive (refreshed per
        # loop iteration); streak-based chain growth requires real
        # quiescence, not just a momentary gap in a sporadic stream
        self.last_arrival_age = float("inf")
        # streak-based chain growth: each chained dispatch pays exactly one
        # fetch round trip, so depth sets the RTT share of decode time on
        # network-attached chips. Sustained quiescence (consecutive chained
        # decode dispatches with nothing else runnable) doubles the depth up
        # to decode_pipeline_cap; any prefill, arrival, or idle pass resets.
        self._chain_streak = 0
        self.decode_pipeline_cap = (
            min(16, self.decode_pipeline * 4) if self.decode_pipeline > 1 else 1
        )
        # worst-case admission-wait budget: while admission is OPEN (free
        # seats and pages), an arrival landing right after a chained dispatch
        # cannot reach the device until the chain retires — run-ahead prefill
        # only queues BEHIND the in-flight bursts. The expected-arrival cap
        # below bounds the mean, not the tail: under sparse traffic
        # (rate ~ 1/s) it allowed ~0.5 s chains, and an unlucky arrival ate
        # the whole chain (measured qps-1.0 admission p50 443 ms — WORSE than
        # qps 2.0). Cap (bursts-1)*burst_seconds by this budget whenever an
        # arrival could actually start, so worst-case wait stays ~100 ms.
        self.chain_wait_budget_s = 0.1
        # whether the driving engine loop can dispatch run-ahead prefills
        # behind an in-flight chain (LLMEngine sets this True — it owns
        # _runahead_prefills). The one-extra-burst chaining floor below the
        # wait budget is ONLY justified by run-ahead (it starts an
        # arrival's prefill DURING the chain); a driver without that path —
        # the safe default for a bare scheduler — or a batch run-ahead
        # cannot serve (logprobs dispatches fetch whole-chain) falls back
        # to bursts=1 when a single burst already exceeds the budget.
        self.runahead_available = False

    # -- api ----------------------------------------------------------------

    def add(self, seq: Sequence) -> None:
        self.waiting.append(seq)

    def abort(self, seq_id: str) -> None:
        for q in (self.waiting, self.running):
            for s in q:
                if s.seq_id == seq_id and not s.finished:
                    self._finish(s, "abort")

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def num_waiting(self) -> int:
        return len(self.waiting)

    def saturated(self, priority: str = "interactive") -> bool:
        """Waiting queue at (or past) its bound — new work should shed.

        Free seats project forward: sequences about to be admitted straight
        into running must not count against the waiting bound, or a batch
        finishing (seats free, queue momentarily still full) would shed
        arrivals a nearly-idle engine could serve — and export a spurious
        engine_saturated gauge the router honors for a whole scrape
        interval. This projection is the single saturation definition: the
        API fast path, the engine-side authoritative bound, and the
        /metrics gauge all read it.

        Class-aware: batch traffic saturates ``interactive_reserve`` waiting
        slots early, so under sustained mixed-class overload every shed
        lands on batch until only the reserved interactive slots remain —
        batch can never starve interactive out of the queue."""
        if self.max_waiting_seqs <= 0:
            return False
        free_seats = max(0, self.max_num_seqs - len(self.running))
        bound = self.max_waiting_seqs + free_seats
        if priority == "batch":
            bound = (
                max(0, self.max_waiting_seqs - self.interactive_reserve)
                + free_seats
            )
        return len(self.waiting) >= bound

    def deadline_for(self, priority: str) -> float:
        """Queue deadline for one SLO class: batch uses its own (typically
        shorter) deadline when configured, else inherits the shared one."""
        if priority == "batch" and self.batch_queue_deadline_s > 0:
            return self.batch_queue_deadline_s
        return self.queue_deadline_s

    def expired_waiting(self, now: Optional[float] = None) -> list[Sequence]:
        """Waiting sequences past their class's queue deadline that can
        still shed CLEANLY: never dispatched (no tokens streamed) and not
        preempted — a preempted sequence already delivered output, so a 429
        is no longer an honest answer and it keeps its place instead."""
        if self.queue_deadline_s <= 0 and self.batch_queue_deadline_s <= 0:
            return []
        now = time.monotonic() if now is None else now
        out = []
        for s in self.waiting:
            if s.first_dispatch_time is not None or getattr(
                s, "preempted", False
            ):
                continue
            deadline = self.deadline_for(getattr(s, "priority", "interactive"))
            if deadline > 0 and now - s.arrival_time > deadline:
                out.append(s)
        return out

    def num_running(self) -> int:
        return len(self.running)

    # -- internals ----------------------------------------------------------

    def _batch_bucket(self, n: int) -> int:
        b = _bucket(n, self.DECODE_BATCH_BUCKETS)
        m = self.batch_multiple
        return -(-b // m) * m

    def _pages_needed(self, tokens: int) -> int:
        return -(-tokens // self.kv.page_size)

    def _try_admit(self) -> None:
        from production_stack_tpu import tracing

        while self.waiting and len(self.running) < self.max_num_seqs:
            # admission order: a preempted head keeps its place (it already
            # streamed tokens — jumping it would stall a live stream), then
            # interactive before batch (FIFO within each class), then FIFO.
            head = self.waiting[0]
            if getattr(head, "preempted", False):
                seq = head
            else:
                seq = next(
                    (
                        s
                        for s in self.waiting
                        if getattr(s, "priority", "interactive") != "batch"
                    ),
                    head,
                )
            # publish a phase-span context for the admission window: offload
            # spill/restore spans recorded inside match_prefix / allocate
            # (kv_manager) nest under the phase of the request that caused
            # them. First admission falls in the queue window; a
            # preempted-then-readmitted sequence is re-admitted inside its
            # prefill (dispatched, no token yet) or decode window, and
            # parenting its restores under the already-closed queue span
            # would double-count that time in the attribution
            if seq.trace is not None and seq.queue_span is None:
                seq.queue_span = seq.trace.child()
                seq.prefill_span = seq.trace.child()
                seq.decode_span = seq.trace.child()
            if seq.first_token_time is not None:
                phase_ctx = seq.decode_span
            elif seq.first_dispatch_time is not None:
                phase_ctx = seq.prefill_span
            else:
                phase_ctx = seq.queue_span
            tr_token = tracing.set_current(phase_ctx)
            try:
                if self.enable_prefix_caching:
                    shared, cached = self.kv.match_prefix(
                        seq.prompt_ids, seq.cache_salt
                    )
                    # never serve the *entire* prompt from cache: the last
                    # token must be recomputed to produce logits
                    if cached >= len(seq.prompt_ids):
                        drop = self._pages_needed(1)
                        for pid in shared[-drop:]:
                            self.kv.free([pid])
                        shared = shared[:-drop]
                        cached = len(shared) * self.kv.page_size
                else:
                    shared, cached = [], 0
                need = self._pages_needed(
                    min(len(seq.prompt_ids) + 16, self.max_model_len + 1)
                ) - len(shared)
                fresh = self.kv.allocate(max(need, 0))
            finally:
                tracing.reset_current(tr_token)
            if fresh is None:
                self.kv.free(shared)
                return
            seq.pages = shared + fresh
            seq.pages_peak = max(seq.pages_peak, len(seq.pages))
            seq.num_cached = cached
            seq.num_computed = cached
            self.waiting.remove(seq)
            self.running.append(seq)

    def _burst_budget(self, seq: Sequence, bursts: int = 1) -> int:
        """Tokens this sequence can still usefully produce in one decode
        dispatch (``bursts`` chained bursts of decode_steps each), capped by
        its remaining max_tokens budget (so near-finished requests don't
        reserve KV for tokens that would be discarded)."""
        return max(1, min(bursts * self.decode_steps,
                          seq.params.max_tokens - len(seq.output_ids)))

    def _spec_limit(self, seq: Sequence) -> int:
        """Max KV length a fused speculative dispatch may reach for ``seq``:
        decode_steps rounds of up to spec_k+1 tokens, capped by the remaining
        max_tokens budget. Verify writes spec_k draft tokens past the current
        length every round, so the cap carries a +spec_k allowance past
        max_model_len for (discarded) overshoot writes."""
        per = self.spec_k + 1
        remaining = max(1, seq.params.max_tokens - len(seq.output_ids))
        iters = max(1, min(self.decode_steps, -(-remaining // per)))
        return min(seq.num_tokens + iters * per, self.max_model_len + self.spec_k)

    def _decode_target_len(self, seq: Sequence, bursts: int = 1) -> int:
        """KV capacity (in tokens) a decode dispatch needs for ``seq``."""
        if self.spec_k:
            return self._spec_limit(seq)
        return min(seq.num_tokens + self._burst_budget(seq, bursts),
                   self.max_model_len + 1)

    def _ensure_decode_page(self, seq: Sequence, bursts: int = 1) -> bool:
        """Make sure the next decode dispatch has KV slots; grow the page list
        if needed (one dispatch of lookahead)."""
        need = self._pages_needed(self._decode_target_len(seq, bursts)) - len(seq.pages)
        if need <= 0:
            return True
        extra = self.kv.allocate(need)
        if extra is None:
            return False
        seq.pages.extend(extra)
        seq.pages_peak = max(seq.pages_peak, len(seq.pages))
        return True

    def _finish(self, seq: Sequence, reason: str) -> None:
        seq.finished = True
        seq.finish_reason = reason
        if seq.finish_time is None:
            seq.finish_time = time.monotonic()
        if self.enable_prefix_caching:
            self.kv.register_filled(
                seq.prompt_ids + seq.output_ids, seq.pages, seq.cache_salt
            )
        self.kv.free(seq.pages)
        seq.pages = []
        if seq in self.running:
            self.running.remove(seq)
        if seq in self.waiting:
            self.waiting.remove(seq)

    # -- step planning ------------------------------------------------------

    def schedule(self) -> Optional[ScheduledBatch]:
        # high-watermark proactive spill: while the pool is nearly full, copy
        # the coldest evictable pages to the offload tier BEFORE an admission
        # or decode-growth allocation forces an eviction — the eviction then
        # frees slots with zero device I/O (cheap no-op below the watermark)
        self.kv.proactive_spill()
        self._try_admit()
        prefilling = [s for s in self.running if s.in_prefill]
        decoding = [s for s in self.running if not s.in_prefill]
        # Alternate prefill chunks with decode bursts when prefill work
        # coexists with RESIDENT DECODE DEMAND: strict prefill priority
        # starves decodes under a steady long-prompt arrival stream
        # (measured 64-token answers taking ~40 s under the multi-round-qa
        # workload) — the whole point of chunked prefill is that decode
        # latency survives long prompts. The gate is demand-driven, not
        # backlog-only: a long-prompt backlog (>= 2 chunks, e.g. one 32k
        # prompt) alternates so the in-flight decodes' inter-token latency
        # stays bounded while it streams through, AND a big resident decode
        # batch (>= prefill_batch rows) alternates even when the backlog is
        # short — each skipped interleave there stalls that many live
        # streams for a whole chunk, which is worse than the one fetch
        # round trip the interleaved burst costs. Small decode batches with
        # a short backlog keep the fast strict-priority path: the flurry
        # clears in a dispatch or two.
        backlog = sum(len(s.prompt_ids) - s.num_computed for s in prefilling)
        demand = len(decoding)
        alternate = (
            demand > 0
            and self._last_kind == "prefill"
            and (
                backlog >= 2 * self.prefill_chunk
                or demand >= max(2, self.prefill_batch)
            )
        )
        # interleave-gate decision surface (flight recorder "sched" events):
        # WHY the loop ran a chunk vs a decode burst is unreconstructable
        # after the fact without these inputs
        self.last_gate = {
            "backlog_tokens": backlog,
            "decode_demand": demand,
            "alternate": alternate,
            "waiting": len(self.waiting),
        }
        if prefilling and not alternate:
            return self._take_prefill(prefilling)
        self._last_kind = "decode"
        if self.running:
            # chain bursts when nothing admissible is waiting to join the
            # batch: a chained dispatch delays the next scheduling decision
            # by (bursts-1) * burst compute, which would hurt arrivals' TTFT.
            # When every seat is taken (running == max_num_seqs), waiting
            # requests CANNOT start regardless — chaining costs them nothing
            # and drains the running set (and so the queue) ~bursts-fold
            # faster on fetch-RTT-bound hosts, which is what decides TTFT
            # under oversubscription (the multi-round-qa shape).
            # _try_admit just ran, so a non-empty waiting queue means its head
            # is blocked — by seats OR by KV pages. Either way nothing new can
            # reach the device until running work retires, which chaining
            # accelerates; treat both as admission-blocked.
            admission_blocked = (
                len(self.running) >= self.max_num_seqs or bool(self.waiting)
            )
            # chaining engages regardless of queue state: an empty queue
            # means nothing is delayed, and a non-empty one (post-_try_admit)
            # means admission is blocked anyway — the wall-time cap below is
            # what protects arrivals while admission is OPEN
            bursts = (
                self.decode_pipeline
                if (
                    not prefilling  # a chain would delay the next chunk
                    and not self.spec_k
                    and self.decode_steps > 1
                    # penalties chain fine: the device history (updated
                    # in-scan) feeds the next burst at the seam
                    # (runner.step_multi_pipelined), so counts never go stale
                )
                else 1
            )
            if (
                bursts > 1
                and self._chain_streak > 0
                and (admission_blocked or self.last_arrival_age > 1.0)
            ):
                # sustained quiescence: double the chain depth per
                # consecutive fully-chained dispatch, up to the cap — depth
                # sets the fetch-RTT share of decode time, and a continuing
                # streak is evidence nothing else wants the device. A
                # SPORADIC arrival stream (gaps shorter than ~1 s) blocks
                # growth even when the instant queue is empty: a deep chain
                # is an admission-wait floor for whoever arrives next —
                # unless admission is blocked anyway, where depth only
                # drains the queue faster.
                bursts = min(
                    bursts << min(self._chain_streak, 4),
                    self.decode_pipeline_cap,
                )
                # don't over-chain past every row's remaining budget: a row
                # at its max_tokens cap is masked for the rest of the chain
                most_left = max(
                    (s.params.max_tokens - len(s.output_ids) for s in decoding),
                    default=1,
                )
                bursts = max(1, min(bursts, -(-most_left // self.decode_steps)))
            # adaptive depth: cap the chain so the EXPECTED number of
            # arrivals stuck waiting behind it stays under ~half a request
            # ((bursts-1) * burst_time * arrival_rate <= 0.5). Quiescent
            # traffic (rate ~ 0) keeps full chaining and its fetch-RTT
            # amortization; under a steady arrival stream chains shorten so
            # a new request's prefill starts within ~a burst of arriving.
            # Irrelevant while admission is blocked: an arrival cannot start
            # until a seat frees, which chaining accelerates.
            if not admission_blocked:
                while (
                    bursts > 1
                    and (bursts - 1) * self.burst_seconds * self.arrival_rate
                    > 0.5
                ):
                    bursts -= 1
                # worst-case bound (not just expected): while an arrival
                # COULD start immediately (free seats + pages), never chain
                # deeper than the wait budget — the expected cap above lets
                # sparse traffic (rate <= ~1/s) keep half-second chains, and
                # whoever arrives mid-chain eats the remainder whole. When a
                # single burst exceeds the budget (long-context decode can
                # run ~0.5 s/burst) a ONE-extra-burst floor survives ONLY if
                # run-ahead prefill can actually serve an arrival during the
                # chain: chained dispatches enable run-ahead
                # (engine._runahead_prefills), which starts an arrival's
                # prefill — and emits its first token — mid-chain, so a
                # 2-burst chain then beats an unchained burst of the same
                # length for exactly the arrival this cap protects. Without
                # run-ahead (engine has none, or the batch wants logprobs —
                # that path fetches whole-chain and dispatches nothing
                # behind it), the floor would make an arrival with admission
                # OPEN wait a full extra burst for nothing: fall back to an
                # unchained dispatch instead.
                extra = int(
                    self.chain_wait_budget_s / max(self.burst_seconds, 1e-4)
                )
                if extra < 1:
                    runahead_ok = self.runahead_available and not any(
                        s.params.logprobs is not None for s in decoding
                    )
                    cap = 2 if runahead_ok else 1
                else:
                    cap = 1 + extra
                bursts = min(bursts, cap)
            if bursts > 1:
                # min_tokens: the EOS ban is fixed for everything one dispatch
                # covers, so a chained dispatch could overshoot the floor by
                # bursts*decode_steps-1 tokens. Cap the chain so rows near
                # their floor get a fresh scheduling decision within one
                # burst of crossing it — the overshoot window stays at the
                # unchained bound (< decode_steps) regardless of pipeline depth.
                for s in decoding:
                    rem = s.params.min_tokens - len(s.output_ids)
                    if rem > 0:
                        bursts = min(bursts, max(1, -(-rem // self.decode_steps)))
            batch = self._plan_decode(decoding, bursts)
            self._chain_streak = (
                self._chain_streak + 1
                if batch is not None and batch.bursts > 1
                else 0
            )
            if batch is None:
                # nothing decodable this pass — fall back to prefill work.
                # RE-DERIVE the prefill set: _plan_decode's page-pressure
                # preemption may have evicted members of the list captured
                # above (freed pages, moved back to waiting), and planning a
                # chunk for a preempted seq would scatter its KV into page 0
                # — a page another live sequence owns.
                prefilling = [s for s in self.running if s.in_prefill]
                if prefilling:
                    return self._take_prefill(prefilling)
            return batch
        return None

    def schedule_prefill_runahead(
        self, exclude_ids: set, allow=None
    ) -> Optional[ScheduledBatch]:
        """Plan a prefill dispatch for sequences DISJOINT from an in-flight
        decode chain (engine run-ahead): new arrivals admit and their chunks
        dispatch while the chain still computes, so the device queues the
        prefill right behind the chain's bursts instead of idling a fetch
        round trip + scheduling turnaround. Disjointness means no mirrored
        state is needed — nothing the chain will apply touches these rows.
        ``allow`` filters candidates BEFORE planning (rows needing staging
        the run-ahead path doesn't do wait for the normal path), so a
        skipped row never perturbs _last_kind/_chain_streak."""
        self._try_admit()
        prefilling = [
            s for s in self.running if s.in_prefill and id(s) not in exclude_ids
        ]
        if allow is not None:
            prefilling = [s for s in prefilling if allow(s)]
        if not prefilling:
            return None
        return self._take_prefill(prefilling)

    def _take_prefill(self, prefilling: list[Sequence]) -> ScheduledBatch:
        """Plan the next prefill dispatch: interactive rows first (their
        TTFT is the SLO under protection), then shortest remaining prompts
        (they finish and start decoding soonest). While interactive prefill
        work is waiting — resident rows that overflow this dispatch, or
        arrivals still queued for a seat — batch's share of the chunk slots
        is capped at ``batch_prefill_share`` so a wall of long batch
        prompts cannot monopolize the prefill pipeline."""
        self._last_kind = "prefill"
        self._chain_streak = 0  # prefill work ends the quiescence streak
        prefilling.sort(
            key=lambda s: (
                getattr(s, "priority", "interactive") == "batch",
                len(s.prompt_ids) - s.num_computed,
            )
        )
        take = prefilling[: self.prefill_batch]
        interactive_waiting = any(
            getattr(s, "priority", "interactive") != "batch"
            for s in prefilling[self.prefill_batch:]
        ) or any(
            getattr(s, "priority", "interactive") != "batch"
            for s in self.waiting
        )
        if interactive_waiting and self.batch_prefill_share < 1.0:
            cap = max(1, int(self.prefill_batch * self.batch_prefill_share))
            inter = [
                s for s in take
                if getattr(s, "priority", "interactive") != "batch"
            ]
            batch_rows = [
                s for s in take
                if getattr(s, "priority", "interactive") == "batch"
            ]
            # always keep >= 1 row so the dispatch makes progress even when
            # everything resident is batch
            take = (inter + batch_rows[:cap]) or take[:1]
        return self._plan_prefill(take)

    def _plan_prefill(self, seqs: list[Sequence]) -> ScheduledBatch:
        chunks = [
            min(len(s.prompt_ids) - s.num_computed, self.prefill_chunk) for s in seqs
        ]
        T = _bucket(max(chunks), self.CHUNK_BUCKETS)
        B = self._batch_bucket(len(seqs))
        max_pages = _bucket(
            max(self._pages_needed(s.num_computed + c) for s, c in zip(seqs, chunks)),
            self.PAGE_BUCKETS,
        )
        input_ids = np.zeros((B, T), np.int32)
        positions = np.full((B, T), -1, np.int32)
        page_table = np.zeros((B, max_pages), np.int32)
        kv_lens = np.zeros((B,), np.int32)
        temperature = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        lora_ids = np.zeros((B,), np.int32)
        want_pen = any(s.params.wants_penalties for s in seqs)
        history = prompt_lens = None
        if want_pen:
            need = max(len(s.prompt_ids) for s in seqs) + 1
            if need <= self.HISTORY_BUCKETS[-1]:
                history = np.zeros(
                    (B, _bucket(need, self.HISTORY_BUCKETS)), np.int32
                )
                prompt_lens = np.zeros((B,), np.int32)
            else:
                want_pen = False  # context beyond the top bucket: skip penalties
        for i, (s, c) in enumerate(zip(seqs, chunks)):
            lo = s.num_computed
            input_ids[i, :c] = s.prompt_ids[lo : lo + c]
            positions[i, :c] = np.arange(lo, lo + c)
            pages = s.pages[:max_pages]
            page_table[i, : len(pages)] = pages
            kv_lens[i] = lo + c
            temperature[i] = s.params.temperature
            top_k[i] = s.params.top_k
            top_p[i] = s.params.top_p
            lora_ids[i] = s.lora_slot
            if history is not None:
                hn = min(len(s.prompt_ids), history.shape[1])
                history[i, :hn] = s.prompt_ids[:hn]
                prompt_lens[i] = len(s.prompt_ids)
        return ScheduledBatch(
            "prefill", list(seqs), input_ids, positions, page_table, kv_lens,
            temperature, top_k, top_p, lora_ids=lora_ids, chunk_sizes=chunks,
            want_logprobs=any(s.params.logprobs is not None for s in seqs),
            want_penalties=want_pen, history=history, prompt_lens=prompt_lens,
        )

    def _plan_decode(
        self, seqs: list[Sequence], bursts: int = 1
    ) -> Optional[ScheduledBatch]:
        ready = []
        # decode-dispatch priority: interactive rows claim their KV growth
        # pages first (stable within class), so when the pool runs dry it is
        # a batch row that fails to grow — and the preemption below evicts
        # batch before any interactive stream is touched
        seqs = sorted(
            seqs,
            key=lambda s: getattr(s, "priority", "interactive") == "batch",
        )
        for s in list(seqs):
            if s not in self.running or s.finished:
                continue  # preempted or finished earlier in this pass
            ok = self._ensure_decode_page(s, bursts)
            while not ok:
                # out of KV pages: preempt the newest other running sequence,
                # preferring batch victims over interactive ones; if there is
                # none, preempt s itself
                others = [x for x in self.running if x is not s]
                if not others:
                    self._preempt(s)
                    break
                victim = max(
                    others,
                    key=lambda x: (
                        getattr(x, "priority", "interactive") == "batch",
                        x.arrival_time,
                    ),
                )
                self._preempt(victim)
                if victim in ready:
                    ready.remove(victim)
                ok = self._ensure_decode_page(s, bursts)
            if ok:
                ready.append(s)
        if not ready:
            return None
        B = self._batch_bucket(len(ready))
        max_pages = _bucket(
            max(self._pages_needed(self._decode_target_len(s, bursts)) for s in ready),
            self.PAGE_BUCKETS,
        )
        input_ids = np.zeros((B, 1), np.int32)
        positions = np.full((B, 1), -1, np.int32)
        page_table = np.zeros((B, max_pages), np.int32)
        kv_lens = np.zeros((B,), np.int32)
        temperature = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        lora_ids = np.zeros((B,), np.int32)
        kv_limits = np.zeros((B,), np.int32)
        history = prompt_lens = None
        want_pen = any(s.params.wants_penalties for s in ready)
        need_hist = 0
        if self.spec_k:
            need_hist = max(self._spec_limit(s) for s in ready)
        elif want_pen:
            # the burst appends sampled tokens at absolute positions
            need_hist = max(
                self._decode_target_len(s, bursts) for s in ready
            )
        if need_hist:
            if need_hist <= self.HISTORY_BUCKETS[-1]:
                # Rebuilt per dispatch: O(B * num_tokens) host memcpy, bounded
                # by the largest bucket (~128 KB/row). Contexts past the top
                # bucket fall back to plain burst decode for this dispatch —
                # the buffer is position-indexed on device, so a truncated
                # head would misplace the current token.
                history = np.zeros((B, _bucket(need_hist, self.HISTORY_BUCKETS)),
                                   np.int32)
                prompt_lens = np.zeros((B,), np.int32)
            else:
                want_pen = False  # context beyond the top bucket
        for i, s in enumerate(ready):
            all_ids = s.prompt_ids + s.output_ids
            input_ids[i, 0] = all_ids[-1]
            positions[i, 0] = s.num_tokens - 1
            pages = s.pages[:max_pages]
            page_table[i, : len(pages)] = pages
            kv_lens[i] = s.num_tokens
            temperature[i] = s.params.temperature
            top_k[i] = s.params.top_k
            top_p[i] = s.params.top_p
            lora_ids[i] = s.lora_slot
            if history is not None:
                if self.spec_k:
                    # speculative: a row stays active while lens + spec_k fits
                    # under kv_limits (verify writes spec_k drafts past lens)
                    kv_limits[i] = min(
                        len(s.pages) * self.kv.page_size, self._spec_limit(s)
                    )
                else:
                    kv_limits[i] = min(
                        len(s.pages) * self.kv.page_size,
                        self.max_model_len,
                        s.num_tokens + self._burst_budget(s, bursts) - 1,
                    )
                hn = min(len(all_ids), history.shape[1])
                history[i, :hn] = all_ids[:hn]
                prompt_lens[i] = min(len(s.prompt_ids), history.shape[1])
            else:
                # device-side burst bound: never write KV past the pages this
                # seq owns, past the model context, or past its max_tokens
                # budget (host discards surplus tokens). With initial lens
                # L0 = num_tokens the burst produces (kv_limits - L0 + 1) real
                # tokens, so a budget of b tokens means kv_limits =
                # num_tokens + b - 1.
                kv_limits[i] = min(
                    len(s.pages) * self.kv.page_size,
                    self.max_model_len,
                    s.num_tokens + self._burst_budget(s, bursts) - 1,
                )
        return ScheduledBatch(
            "decode", ready, input_ids, positions, page_table, kv_lens,
            temperature, top_k, top_p, lora_ids=lora_ids, kv_limits=kv_limits,
            history=history, bursts=bursts,
            want_logprobs=any(s.params.logprobs is not None for s in ready),
            want_penalties=want_pen, prompt_lens=prompt_lens,
        )

    def _preempt(self, seq: Sequence) -> None:
        """Return a running sequence to the waiting queue, dropping its KV."""
        self.kv.free(seq.pages)
        seq.pages = []
        seq.num_computed = 0
        seq.num_cached = 0
        seq.preempted = True  # vllm:num_requests_swapped until re-admitted
        self.preemptions_total += 1
        if seq in self.running:
            self.running.remove(seq)
        self.waiting.insert(0, seq)

    def num_swapped(self) -> int:
        """Preempted sequences parked in the waiting queue — the analogue of
        vLLM's num_requests_swapped (ours drop/respill KV through the offload
        tiers instead of a dedicated swap space)."""
        return sum(1 for s in self.waiting if getattr(s, "preempted", False))

    # -- result application -------------------------------------------------

    def apply_step(self, batch: ScheduledBatch, token_ids: np.ndarray, eos_token_id: int):
        """Apply sampled tokens; returns list of (seq, new_token, row, col) —
        row/col index into ``token_ids`` so callers can align per-token
        side data (logprobs).

        ``token_ids`` is [B] (prefill / single-step decode), [B, k] (fused
        multi-step decode), or [B, steps, 1+spec_k] with -1 padding
        (speculative decode); surplus tokens after a sequence finishes
        (EOS, max_tokens, context limit) and -1 padding are discarded.
        """
        tokens = np.asarray(token_ids)
        if tokens.ndim == 3:
            tokens = tokens.reshape(tokens.shape[0], -1)
        if tokens.ndim == 1:
            tokens = tokens[:, None]
        events = []

        def consume(s, tok, i, j) -> None:
            s.output_ids.append(tok)
            events.append((s, tok, i, j))
            if (
                not s.params.ignore_eos
                and tok == eos_token_id
                and len(s.output_ids) >= s.params.min_tokens
            ):
                self._finish(s, "stop")
            elif len(s.output_ids) >= s.params.max_tokens:
                self._finish(s, "length")
            elif s.num_tokens >= self.max_model_len:
                self._finish(s, "length")

        if batch.kind == "prefill":
            for i, s in enumerate(batch.seqs):
                if s.finished:
                    continue
                c = batch.chunk_sizes[i]
                s.num_computed += c
                if s.in_prefill:
                    continue  # more prompt chunks to go
                if self.enable_prefix_caching:
                    # register the prompt's full pages NOW (not at finish):
                    # concurrent requests sharing the prompt — parallel
                    # sampling siblings, common system prompts — hit the
                    # cache immediately instead of re-prefilling. Idempotent;
                    # finish re-registers with the output included.
                    self.kv.register_filled(
                        s.prompt_ids, s.pages, s.cache_salt
                    )
                if s.first_token_time is None:
                    s.first_token_time = time.monotonic()
                consume(s, int(tokens[i, 0]), i, 0)
            return events

        for j in range(tokens.shape[1]):
            for i, s in enumerate(batch.seqs):
                tok = int(tokens[i, j])
                if tok >= 0 and not s.finished:
                    consume(s, tok, i, j)
        return events
