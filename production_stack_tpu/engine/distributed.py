"""Multi-host serving: leader/follower device-dispatch replication.

JAX's multi-controller runtime requires every process to dispatch the SAME
jitted programs in the same order (each process drives its local chips; XLA
collectives stitch them together over ICI/DCN). The reference gets multi-host
execution from Ray (`ray-cluster.yaml` spins a cluster so vLLM can place
pipeline stages; /root/reference helm/templates/ray-cluster.yaml:515-566).
Here the JAX coordination service replaces Ray's GCS and a thin TCP fan-out
replaces its task RPC:

- Process 0 (leader) runs the real engine: HTTP API, scheduler, tokenizer,
  prefix cache. Every device call (step/step_multi/...) is first broadcast —
  method name + numpy args, length-prefixed pickle over TCP — to all
  followers, then executed locally.
- Processes 1..N-1 (followers) run ``follower_loop``: receive each descriptor
  and invoke the identical method on their local ModelRunner. Same seed ⇒
  same RNG splits ⇒ identical programs; XLA's collectives do the rest.
- ``jax.distributed.initialize`` is the rendezvous barrier — the analogue of
  the reference's ``EXPECTED_NODES`` wait loop (ray-cluster.yaml:46-47).

Sampled tokens are replicated across processes (the step functions constrain
their outputs to a fully-replicated sharding), so the leader's host fetch
sees the whole batch without extra collectives.

Failure model: K8s restarts the whole StatefulSet on any pod failure — a
multi-controller JAX program cannot survive losing a process, which matches
the reference's Ray-cluster behavior (head restart ⇒ full redeploy).
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Optional

from production_stack_tpu.utils.logging import init_logger

logger = init_logger(__name__)

_LEN = struct.Struct("!Q")

# runner methods replicated to followers. get_page is deliberately absent:
# host fetches are leader-local (each process can only address its own
# shards), so KV offload tiers are unsupported in multi-host mode.
REPLICATED = (
    "step",
    "step_multi",
    "step_multi_pipelined",
    "step_spec",
    "encode",
    "set_lora_slot",
    "clear_lora_slot",
    "set_page",
    "reset_kv",
)


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> Optional[bytes]:
    hdr = b""
    while len(hdr) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = _LEN.unpack(hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class StepBroadcaster:
    """Leader side: accepts follower connections, fans out call descriptors.

    The constructor blocks until all ``num_followers`` have connected — by
    then ``jax.distributed.initialize`` has already barriered, so followers
    are guaranteed to be dialing.
    """

    def __init__(self, port: int, num_followers: int, *, timeout: float = 300.0):
        self._lock = threading.Lock()
        self._socks: list[socket.socket] = []
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", port))
        srv.listen(num_followers)
        srv.settimeout(timeout)
        logger.info("leader waiting for %d follower(s) on :%d", num_followers, port)
        for _ in range(num_followers):
            conn, addr = srv.accept()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(conn)
            logger.info("follower connected from %s", addr)
        srv.close()

    def broadcast(self, method: str, args: tuple, kwargs: dict) -> None:
        payload = pickle.dumps((method, args, kwargs), protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            for s in self._socks:
                _send_msg(s, payload)

    def close(self) -> None:
        with self._lock:
            for s in self._socks:
                try:
                    _send_msg(s, pickle.dumps(None))
                    s.close()
                except OSError:
                    pass
            self._socks.clear()


class BroadcastingRunner:
    """Wraps a ModelRunner: replicated methods broadcast before local dispatch.

    Host-side return values come from the local call — outputs are
    replicated-sharded by the step functions, so the leader's fetch sees the
    global batch.
    """

    def __init__(self, runner, broadcaster: StepBroadcaster):
        self._runner = runner
        self._bc = broadcaster

    def __getattr__(self, name):
        attr = getattr(self._runner, name)
        if name not in REPLICATED or not callable(attr):
            return attr

        def call(*args, **kwargs):
            self._bc.broadcast(name, args, kwargs)
            return attr(*args, **kwargs)

        return call


def follower_loop(runner, leader_host: str, port: int, *, timeout: float = 300.0) -> None:
    """Follower side: dial the leader and replay every call descriptor on the
    local runner until the leader closes the stream.

    Connection attempts retry until ``timeout``: engine construction time
    varies across pods (checkpoint load), so a follower may be ready to dial
    before the leader has bound the sync port — a refused connect is
    expected startup noise, not an error."""
    import time as time_mod

    deadline = time_mod.monotonic() + timeout
    while True:
        try:
            sock = socket.create_connection((leader_host, port), timeout=timeout)
            break
        except (ConnectionRefusedError, OSError):
            if time_mod.monotonic() >= deadline:
                raise
            time_mod.sleep(1.0)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    logger.info("follower connected to leader %s:%d", leader_host, port)
    while True:
        payload = _recv_msg(sock)
        if payload is None:
            logger.info("leader stream closed; follower exiting")
            return
        msg = pickle.loads(payload)
        if msg is None:
            logger.info("leader shutdown; follower exiting")
            return
        method, args, kwargs = msg
        if method not in REPLICATED:
            raise RuntimeError(f"follower received non-replicated method {method!r}")
        getattr(runner, method)(*args, **kwargs)
