"""Streaming tool-call extraction from model output.

The reference stack gets tool calling from vLLM engine flags
(`--enable-auto-tool-choice --tool-call-parser ...`; its tutorial
/root/reference/tutorials/13-tool-enabled-installation.md simply turns them
on). We own the engine, so the parser lives here: it splits the token stream
into user-visible content and OpenAI `tool_calls` objects, incrementally, so
the chat endpoint can stream deltas.

Two wire formats cover the mainstream open models:

- ``hermes``: ``<tool_call>{"name": ..., "arguments": {...}}</tool_call>``
  (Hermes/Qwen family). Content may surround the tagged blocks.
- ``json``: the whole completion is a bare JSON object or array of objects —
  ``{"name": ..., "parameters": {...}}`` — the Llama-3.x chat-template
  convention.

``auto`` watches for either trigger: a ``<tool_call>`` tag anywhere, or a
completion whose first non-whitespace character opens a JSON container. If a
candidate never parses as a tool call, the buffered text is flushed back as
ordinary content — a model that happens to answer with JSON still works.
"""

from __future__ import annotations

import json
import uuid

_HERMES_OPEN = "<tool_call>"
_HERMES_CLOSE = "</tool_call>"


def _mk_call(name: str, args) -> dict:
    return {
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {
            "name": name,
            # arguments is a JSON *string* per the OpenAI schema
            "arguments": args if isinstance(args, str) else json.dumps(args),
        },
    }


def _parse_call_obj(obj) -> "dict | None":
    """{"name": ..., "arguments"|"parameters": ...} -> tool_call, else None."""
    if not isinstance(obj, dict) or not isinstance(obj.get("name"), str):
        return None
    args = obj.get("arguments", obj.get("parameters", {}))
    return _mk_call(obj["name"], args)


def parse_tool_calls(text: str, style: str = "auto") -> "tuple[str, list[dict]]":
    """Non-streaming split of a full completion into (content, tool_calls)."""
    p = StreamingToolParser(style)
    events = p.push(text) + p.finish()
    content = "".join(e[1] for e in events if e[0] == "content")
    return content, p.tool_calls


class StreamingToolParser:
    """Incremental splitter. ``push(delta)``/``finish()`` return event lists:
    ``("content", str)`` for pass-through text and ``("call", tool_call)``
    for each completed call (also appended to ``self.tool_calls``)."""

    def __init__(self, style: str = "auto"):
        if style not in ("auto", "hermes", "json", "off"):
            raise ValueError(f"unknown tool parser style {style!r}")
        self.style = style
        self.tool_calls: list[dict] = []
        self._buf = ""          # text not yet classified
        self._mode = "scan"     # scan | hermes_body | json_tail
        self._seen_content = False

    # -- internals ----------------------------------------------------------

    def _emit_calls(self, objs) -> list:
        calls = [_parse_call_obj(obj) for obj in objs]
        if any(c is None for c in calls):
            return []  # any malformed member voids the whole candidate
        self.tool_calls.extend(calls)
        return [("call", c) for c in calls]

    def _try_json(self, text: str) -> list:
        """Parse a complete json-style candidate; [] if it isn't one."""
        try:
            obj = json.loads(text)
        except ValueError:
            return []
        objs = obj if isinstance(obj, list) else [obj]
        if not objs:
            return []
        return self._emit_calls(objs)

    # -- api ----------------------------------------------------------------

    def push(self, delta: str) -> list:
        if self.style == "off" or not delta:
            return [("content", delta)] if delta else []
        self._buf += delta
        events: list = []
        while True:
            if self._mode == "hermes_body":
                end = self._buf.find(_HERMES_CLOSE)
                if end < 0:
                    return events  # wait for the closing tag
                body = self._buf[: end]
                self._buf = self._buf[end + len(_HERMES_CLOSE):]
                got = self._try_json(body.strip())
                if not got:
                    # not a tool call after all: surface the block verbatim
                    events.append(("content", _HERMES_OPEN + body + _HERMES_CLOSE))
                events.extend(got)
                self._mode = "scan"
                continue

            if self._mode == "json_tail":
                return events  # everything buffers until finish()

            # scan mode: watch for a hermes tag / leading JSON container
            if self.style in ("auto", "hermes"):
                start = self._buf.find(_HERMES_OPEN)
                if start >= 0:
                    if start:
                        self._seen_content = True
                        events.append(("content", self._buf[:start]))
                    self._buf = self._buf[start + len(_HERMES_OPEN):]
                    self._mode = "hermes_body"
                    continue
            if (
                self.style in ("auto", "json")
                and not self._seen_content
                and self._buf.lstrip()[:1] in ("{", "[")
            ):
                self._mode = "json_tail"
                return events
            # plain content — but hold back any suffix that could be the
            # start of a hermes tag (or, pre-content, leading whitespace
            # that may precede a JSON container)
            hold = 0
            if self.style in ("auto", "hermes"):
                for k in range(min(len(_HERMES_OPEN) - 1, len(self._buf)), 0, -1):
                    if _HERMES_OPEN.startswith(self._buf[-k:]):
                        hold = k
                        break
            if not self._seen_content and not self._buf.strip():
                return events  # all-whitespace so far: keep buffering
            out = self._buf[: len(self._buf) - hold]
            if out:
                self._seen_content = True
                events.append(("content", out))
            self._buf = self._buf[len(self._buf) - hold:]
            return events

    def finish(self) -> list:
        """Flush at end-of-stream; unresolved candidates revert to content."""
        events: list = []
        if self._mode == "json_tail":
            events = self._try_json(self._buf.strip())
        elif self._mode == "hermes_body":
            # unclosed tag: give the raw text back
            self._buf = _HERMES_OPEN + self._buf
        if not events and self._buf:
            events = [("content", self._buf)]
        self._buf = ""
        self._mode = "scan"
        return events
