"""Load model weights.

Two paths:
- preset name (llama-debug / llama-3.2-1b / llama-3-8b ...): seeded random
  init — used by tests, benchmarks, and hermetic environments.
- local HuggingFace directory (config.json + *.safetensors): production path;
  weights live on a PVC exactly like the reference's HF_HOME cache
  (helm/templates/deployment-vllm-multi.yaml:191-196 in /root/reference).

HF Llama layout is mapped onto the layer-stacked tree models/llama.py uses
(per-layer tensors stacked on a leading [L] axis for the scan).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.models import llama


def is_hf_dir(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(os.path.join(path, "config.json"))


def load_model(model: str, seed: int = 0, max_model_len: int | None = None):
    """Returns (LlamaConfig, params)."""
    if is_hf_dir(model):
        return load_llama_from_hf(model)
    if model in llama.PRESETS:
        cfg = llama.PRESETS[model]
        if max_model_len:
            import dataclasses

            cfg = dataclasses.replace(cfg, max_model_len=max_model_len)
        return cfg, llama.init_params(cfg, jax.random.key(seed))
    raise ValueError(
        f"model '{model}' is neither a preset ({sorted(llama.PRESETS)}) nor a local HF dir"
    )


def _safetensor_shards(path: str):
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors in {path}")
    tensors: dict[str, Any] = {}
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                tensors[key] = f.get_tensor(key)
    return tensors


def load_llama_from_hf(path: str) -> tuple[llama.LlamaConfig, dict]:
    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    cfg = llama.LlamaConfig.from_hf_config(hf_cfg)
    t = _safetensor_shards(path)
    L = cfg.num_layers
    dt = cfg.dtype

    def get(name: str) -> np.ndarray:
        return np.asarray(t[name])

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        ws = [get(fmt.format(i)) for i in range(L)]
        arr = np.stack([w.T if transpose else w for w in ws])
        return jnp.asarray(arr, dt)

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dt),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", transpose=False),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight", transpose=False),
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight"),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight"),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight"),
        },
        "final_norm": jnp.asarray(get("model.norm.weight"), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T, dt)
    return cfg, params
