"""Load model weights.

Two paths:
- preset name (llama-debug / llama-3.2-1b / qwen2.5-7b / mixtral-8x7b /
  opt-125m ...): seeded random init — used by tests, benchmarks, and hermetic
  environments.
- local HuggingFace directory (config.json + *.safetensors): production path;
  weights live on a PVC exactly like the reference's HF_HOME cache
  (helm/templates/deployment-vllm-multi.yaml:191-196 in /root/reference).
  Architecture is dispatched on `config.json["architectures"][0]`
  (Llama/Mistral/Qwen2/Mixtral → models/llama.py; OPT → models/opt.py).

HF per-layer tensors are mapped onto the layer-stacked trees the models use
(every per-layer weight stacked on a leading [L] axis for the scan).

Returns (module, config, params) — the module is the models/* family module
whose `forward` the runner will jit.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu import models
from production_stack_tpu.models import gemma2, llama, opt


def is_hf_dir(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(os.path.join(path, "config.json"))


def load_model(model: str, seed: int = 0, max_model_len: int | None = None):
    """Returns (module, config, params)."""
    if is_hf_dir(model):
        mod, cfg, params = load_from_hf(model)
        if max_model_len:
            if mod is opt and max_model_len > cfg.max_model_len:
                # OPT's learned position table is checkpoint-sized; it cannot
                # be extended (positions past it would clamp-gather silently)
                raise ValueError(
                    f"max_model_len={max_model_len} exceeds OPT position table "
                    f"({cfg.max_model_len})"
                )
            cfg = dataclasses.replace(cfg, max_model_len=max_model_len)
    else:
        hit = models.find_preset(model)
        if hit is None:
            names = sorted(n for m in models.MODULES for n in m.PRESETS)
            raise ValueError(
                f"model '{model}' is neither a preset ({names}) nor a local HF dir"
            )
        mod, cfg = hit
        if max_model_len:
            # before init_params: OPT sizes its position table from this
            cfg = dataclasses.replace(cfg, max_model_len=max_model_len)
        params = mod.init_params(cfg, jax.random.key(seed))
    return mod, cfg, params


def _safetensor_shards(path: str):
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors in {path}")
    tensors: dict[str, Any] = {}
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                tensors[key] = f.get_tensor(key)
    return tensors


def load_from_hf(path: str):
    """Load any supported architecture from a local HF directory."""
    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    arch = (hf_cfg.get("architectures") or ["LlamaForCausalLM"])[0]
    mod = models.module_for_arch(arch)
    if mod is opt:
        cfg, params = _load_opt(hf_cfg, path)
    elif mod is gemma2:
        cfg, params = _load_gemma2(hf_cfg, path)
    else:
        cfg, params = _load_llama_family(hf_cfg, path)
    return mod, cfg, params


def _weight_helpers(tensors: dict, num_layers: int, dtype):
    def get(name: str) -> np.ndarray:
        return np.asarray(tensors[name])

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        ws = [get(fmt.format(i)) for i in range(num_layers)]
        arr = np.stack([w.T if transpose else w for w in ws])
        return jnp.asarray(arr, dtype)

    return get, stack


def _load_llama_family(hf_cfg: dict, path: str) -> tuple[llama.LlamaConfig, dict]:
    cfg = llama.LlamaConfig.from_hf_config(hf_cfg)
    t = _safetensor_shards(path)
    dt = cfg.dtype
    get, stack = _weight_helpers(t, cfg.num_layers, dt)

    layers = {
        "attn_norm": stack("model.layers.{}.input_layernorm.weight", transpose=False),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
        "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight", transpose=False),
    }
    if cfg.attention_bias:
        layers["bq"] = stack("model.layers.{}.self_attn.q_proj.bias", transpose=False)
        layers["bk"] = stack("model.layers.{}.self_attn.k_proj.bias", transpose=False)
        layers["bv"] = stack("model.layers.{}.self_attn.v_proj.bias", transpose=False)
    if cfg.num_experts:
        # Mixtral: block_sparse_moe.gate + per-expert w1 (gate), w2 (down), w3 (up)
        L, E = cfg.num_layers, cfg.num_experts

        def stack_experts(w: str) -> jnp.ndarray:
            arr = np.stack([
                np.stack([
                    get(f"model.layers.{i}.block_sparse_moe.experts.{e}.{w}.weight").T
                    for e in range(E)
                ])
                for i in range(L)
            ])  # [L, E, in, out]
            return jnp.asarray(arr, dt)

        layers["moe_router"] = stack("model.layers.{}.block_sparse_moe.gate.weight")
        layers["moe_gate"] = stack_experts("w1")
        layers["moe_down"] = stack_experts("w2")
        layers["moe_up"] = stack_experts("w3")
    else:
        layers["w_gate"] = stack("model.layers.{}.mlp.gate_proj.weight")
        layers["w_up"] = stack("model.layers.{}.mlp.up_proj.weight")
        layers["w_down"] = stack("model.layers.{}.mlp.down_proj.weight")

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dt),
        "layers": layers,
        "final_norm": jnp.asarray(get("model.norm.weight"), dt),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T, dt)
    return cfg, params


def _load_opt(hf_cfg: dict, path: str) -> tuple[opt.OPTConfig, dict]:
    cfg = opt.OPTConfig.from_hf_config(hf_cfg)
    raw = _safetensor_shards(path)
    dt = cfg.dtype
    # OPTForCausalLM checkpoints prefix with "model."; bare OPTModel ones don't.
    t = {
        (k[len("model."):] if k.startswith("model.") else k): v
        for k, v in raw.items()
    }
    get, stack = _weight_helpers(t, cfg.num_layers, dt)
    lf = "decoder.layers.{}."
    params = {
        "embed": jnp.asarray(get("decoder.embed_tokens.weight"), dt),
        "pos_embed": jnp.asarray(get("decoder.embed_positions.weight"), dt),
        "layers": {
            "attn_norm_w": stack(lf + "self_attn_layer_norm.weight", transpose=False),
            "attn_norm_b": stack(lf + "self_attn_layer_norm.bias", transpose=False),
            "wq": stack(lf + "self_attn.q_proj.weight"),
            "bq": stack(lf + "self_attn.q_proj.bias", transpose=False),
            "wk": stack(lf + "self_attn.k_proj.weight"),
            "bk": stack(lf + "self_attn.k_proj.bias", transpose=False),
            "wv": stack(lf + "self_attn.v_proj.weight"),
            "bv": stack(lf + "self_attn.v_proj.bias", transpose=False),
            "wo": stack(lf + "self_attn.out_proj.weight"),
            "bo": stack(lf + "self_attn.out_proj.bias", transpose=False),
            "mlp_norm_w": stack(lf + "final_layer_norm.weight", transpose=False),
            "mlp_norm_b": stack(lf + "final_layer_norm.bias", transpose=False),
            "fc1": stack(lf + "fc1.weight"),
            "fc1_b": stack(lf + "fc1.bias", transpose=False),
            "fc2": stack(lf + "fc2.weight"),
            "fc2_b": stack(lf + "fc2.bias", transpose=False),
        },
        "final_norm_w": jnp.asarray(get("decoder.final_layer_norm.weight"), dt),
        "final_norm_b": jnp.asarray(get("decoder.final_layer_norm.bias"), dt),
    }
    return cfg, params


def _load_gemma2(hf_cfg: dict, path: str) -> tuple["gemma2.Gemma2Config", dict]:
    cfg = gemma2.Gemma2Config.from_hf_config(hf_cfg)
    t = _safetensor_shards(path)
    dt = cfg.dtype
    get, stack = _weight_helpers(t, cfg.num_layers, dt)
    lf = "model.layers.{}."
    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dt),
        "layers": {
            "attn_norm": stack(lf + "input_layernorm.weight", transpose=False),
            "post_attn_norm": stack(lf + "post_attention_layernorm.weight", transpose=False),
            "mlp_norm": stack(lf + "pre_feedforward_layernorm.weight", transpose=False),
            "post_mlp_norm": stack(lf + "post_feedforward_layernorm.weight", transpose=False),
            "wq": stack(lf + "self_attn.q_proj.weight"),
            "wk": stack(lf + "self_attn.k_proj.weight"),
            "wv": stack(lf + "self_attn.v_proj.weight"),
            "wo": stack(lf + "self_attn.o_proj.weight"),
            "w_gate": stack(lf + "mlp.gate_proj.weight"),
            "w_up": stack(lf + "mlp.up_proj.weight"),
            "w_down": stack(lf + "mlp.down_proj.weight"),
        },
        "final_norm": jnp.asarray(get("model.norm.weight"), dt),
    }
    return cfg, params


def load_llama_from_hf(path: str) -> tuple[llama.LlamaConfig, dict]:
    """Back-compat shim (Llama-family only)."""
    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    return _load_llama_family(hf_cfg, path)
