"""Engine configuration + CLI.

Flag surface mirrors what the reference stack passes to `vllm serve`
(helm/templates/deployment-vllm-multi.yaml:96-186, ray-cluster.yaml:520-605 in
/root/reference): tensor/pipeline parallel sizes, chunked prefill, prefix
caching, max len, sleep mode — plus TPU-specific knobs (page count, buckets).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional


@dataclasses.dataclass
class EngineConfig:
    model: str = "llama-debug"          # preset name or local HF directory
    served_model_name: Optional[str] = None
    tokenizer: Optional[str] = None     # defaults to model dir when it is a path
    host: str = "0.0.0.0"
    port: int = 8100
    max_num_seqs: int = 64
    max_model_len: int = 4096
    # engine-side admission control (overload survival): bound on the waiting
    # queue — at or past it new generation requests are SHED with 429 +
    # Retry-After instead of queued into unbounded TTFT (0 = unbounded,
    # matching vLLM). Export: vllm:engine_saturated / num_requests_shed_total.
    max_waiting_seqs: int = 0
    # per-request queue deadline: a request still undispatched after this many
    # seconds is shed (429) by the engine loop (0 = never shed by age)
    queue_deadline_s: float = 0.0
    # Retry-After seconds advertised on shed responses
    shed_retry_after_s: float = 1.0
    # SLO classes (docs/failure-handling.md "Priority classes & graceful
    # degradation"): waiting-queue slots reserved for interactive requests —
    # batch traffic saturates (sheds) this many slots early, so batch load
    # can never starve interactive out of a bounded queue
    interactive_reserve: int = 1
    # queue deadline applied to batch-class requests only (0 = inherit
    # queue_deadline_s); a shorter batch deadline makes the engine loop
    # expire batch out of a congested queue before any interactive request
    batch_queue_deadline_s: float = 0.0
    # max share of a prefill dispatch's chunk slots batch may hold while an
    # interactive prefill is waiting (1.0 = no cap)
    batch_prefill_share: float = 0.5
    # KV page size (tokens). Larger pages mean fewer (bigger) page DMAs per
    # decode step: measured on v5e (llama-3.2-1b class, B=16, 1k ctx, with
    # deferred-burst KV + stacked-pool streaming) decode runs 1037 tok/s at
    # page 16, 1387 at 32, 1706 at 64, 1954 at 128 — DMA issue rate, not
    # bandwidth, is the limiter at small pages. The sharing-granularity cost
    # of 64 over 32 is measured, not assumed: on the multi-round-qa headline
    # workload (32 users x 5 rounds, ~1k-token shared prefix, through the
    # full router+engine stack on one v5e chip) the prefix-cache hit rate is
    # 93.59% at page 64 vs 93.76% at page 32 — a 0.17% delta — while page 32
    # costs ~20% generation throughput (224.5 vs 178.6 tok/s same run). 64
    # stays the default; it is also 4x finer sharing than the reference's
    # 256-token LMCache chunks.
    page_size: int = 64
    num_pages: Optional[int] = None     # default: sized from kv_cache_memory_gb
    kv_cache_memory_gb: float = 4.0
    prefill_chunk: int = 512
    prefill_batch: int = 4
    # fused decode burst: tokens produced per device program dispatch. >1
    # amortizes host<->device round trips (runner.step_multi); surplus tokens
    # after EOS are discarded host-side. With speculative decoding on, this is
    # the number of fused draft+verify rounds per dispatch instead.
    decode_steps: int = 8
    # chained decode bursts per dispatch when no requests are waiting: burst
    # j+1's input token is fed from burst j's device-resident output, so a
    # chain of m bursts pays one fetch round trip instead of m (matters on
    # network-attached TPUs where a fetch costs ~compute-of-a-burst). Arrivals
    # during a chain wait up to (pipeline-1) extra bursts before prefill.
    # Tradeoff: chaining doubles the decode program variants the engine
    # compiles ((batch, pages) buckets x {chained, unchained}) — enable for
    # long-lived serving pods, not for short benchmark windows.
    decode_pipeline: int = 1
    # speculative decoding (prompt-lookup/n-gram, fused on device): draft
    # length per round; 0 disables. The TPU-native analogue of vLLM's ngram
    # speculator — decode becomes parallel verify instead of serial steps.
    speculative_k: int = 0
    speculative_ngram: int = 3
    enable_prefix_caching: bool = True
    enable_chunked_prefill: bool = True
    # attention implementation, threaded into the model config:
    # auto | xla | pallas | pallas_prefill | pallas_interpret (ModelRunner
    # resolves "auto"; "pallas" = the decode kernel, "pallas_prefill"
    # additionally runs the EXPERIMENTAL chunked-prefill kernel — currently
    # XLA-parity on v5e, models/llama.py)
    attn_impl: str = "auto"
    # tool-call extraction from chat completions (engine/tool_parser.py):
    # auto | hermes | json | off. The reference reaches this via vLLM's
    # --tool-call-parser flag (tutorials/13); we own the engine, so the
    # streaming parser lives here.
    tool_call_parser: str = "auto"
    # KV write placement (threaded into the model config): "pre" writes each
    # layer's K/V into the pool before attending; "post" attends over the
    # stale pool + in-register chunk K/V and commits all layers with one
    # batched scatter after the layer scan (avoids per-layer pool copies)
    kv_write_mode: str = "post"
    # decode-kernel memory pipeline tuning (threaded into the model config;
    # ops/pallas/paged_attention.py). decode_pages_per_block: KV pages per
    # packed grid cell (0 = auto: ~128 slots, ~512 for >=128-page buckets).
    # decode_prefetch_pages: depth of the kernel's VMEM page-copy ring — how
    # many page DMAs stay in flight ahead of compute (0 = auto: up to 8
    # within a ~2 MB VMEM budget per pool array). Retune with
    # scripts/profile_decode.py, which reports achieved HBM GB/s per
    # (batch, context, page_size) bucket.
    decode_pages_per_block: int = 0
    decode_prefetch_pages: int = 0
    # prefill-kernel memory pipeline tuning (threaded into the model config;
    # ops/pallas/prefill_attention.py). prefill_pages_per_block: KV pages
    # landed CONTIGUOUSLY per packed grid cell and folded as one wide
    # matmul (0 = auto: ~512 slots). prefill_prefetch_pages: page DMAs kept
    # in flight ahead of the cell being consumed (0 = auto: ~2 cells'
    # worth). Retune with scripts/profile_prefill.py, which reports
    # achieved HBM GB/s + tok/s per (chunk, context) bucket.
    prefill_pages_per_block: int = 0
    prefill_prefetch_pages: int = 0
    # fused paged-KV write: the prefill kernel commits the chunk's K/V to
    # its pool pages in-kernel (pools aliased input->output), replacing the
    # post-scan scatter pass — the chunk's KV crosses HBM once instead of
    # three times. Disable to fall back to the stacked-output + scatter
    # path (same numerics; tests assert bit-identical pools).
    prefill_fused_kv_write: bool = True
    # KV cache dtype (threaded into the model config; ops/quant.py):
    # auto (= model dtype) | bf16 | fp16 | int8. "int8" stores pages
    # quantized with per-page per-kv-head scales in a parallel scales pool:
    # the bandwidth-bound long-context decode step streams HALF the HBM
    # bytes, and the same kv_cache_memory_gb holds ~2x the tokens.
    # Dequantization happens inside the kernels' VMEM copy rings (fp KV
    # never round-trips through HBM); quantization inside the fused prefill
    # write and on the decode feedback commit. Offload/warm-start/
    # directory/migration blobs ship the int8 bytes + scales (serde v3,
    # CRC-framed, tp split/join-aware). Quality: ~1-1.5% relative logit
    # error measured (docs/benchmarking.md); bench.py records the greedy
    # token-match delta. Requires kv_write_mode=post; not compatible with
    # speculative_k>0, sp/pp meshes, disagg kv_role, or device KV transfer.
    kv_cache_dtype: str = "auto"
    # tensor parallelism: attention heads + MLP hidden shard over the tp mesh
    # axis (parallel/shardings.py); the paged KV pool becomes per-chip — each
    # chip holds its kv-head shard of every page, so page ids, chains, hashes,
    # eviction, offload, and migration are tp-invariant (one logical page = N
    # physical head-shards; serde blobs gather/scatter shards at the tier
    # boundary — docs/multichip-serving.md). ``--tensor-parallel N`` is
    # accepted as an alias (reference vLLM spells it -tp).
    tensor_parallel_size: int = 1
    data_parallel_size: int = 1
    # sequence/context parallelism: long prefill chunks run ring attention
    # over the sp mesh axis (parallel/ring_attention.py) and activations
    # shard their token dim; decode is unaffected. Absent in the reference
    # (SURVEY.md §2.3) — first-class here.
    sequence_parallel_size: int = 1
    # expert parallelism: MoE expert weights shard over the ep mesh axis
    # (parallel/shardings.py moe_* specs); dense models ignore it.
    expert_parallel_size: int = 1
    # pipeline parallelism: the layer stack splits into contiguous stages
    # over the pp mesh axis; microbatches relay stage-to-stage inside the
    # jitted step (parallel/pipeline.py serving_layer_pipeline). The
    # reference reaches this via Ray + vLLM --pipeline-parallel-size
    # (ray-cluster.yaml:560-566); here it is one SPMD program, no Ray.
    pipeline_parallel_size: int = 1
    # multi-host serving (StatefulSet choreography, tutorial 15): process 0
    # serves HTTP and broadcasts device dispatches; others follow. The
    # coordinator address doubles as the JAX rendezvous (replaces the
    # reference's Ray cluster + EXPECTED_NODES barrier).
    distributed_coordinator: Optional[str] = None   # host:port of process 0
    distributed_num_processes: int = 1
    distributed_process_id: Optional[int] = None    # default: hostname -N suffix
    worker_sync_port: int = 8477
    enable_sleep_mode: bool = False
    # register unauthenticated state-mutating debug endpoints (POST
    # /metrics/reset); benchmark and test harnesses only — a production
    # server must not let any client wipe its observability windows
    enable_debug_endpoints: bool = False
    # persistent XLA compilation cache directory (utils/compile_cache.py);
    # None resolves via $PSTPU_COMPILE_CACHE_DIR then ~/.cache. In K8s this
    # is a PVC (helm values.compileCache) so pod restarts start warm instead
    # of paying 20-40 s per program variant.
    compilation_cache_dir: Optional[str] = None
    seed: int = 0
    # multi-LoRA serving (reference: vLLM --enable-lora + load/unload endpoints,
    # helm/templates/deployment-vllm-multi.yaml:197-207)
    enable_lora: bool = False
    max_loras: int = 4
    max_lora_rank: int = 16
    lora_target_modules: str = "q_proj,k_proj,v_proj,o_proj"
    # KV offload (LMCache-equivalent) wiring
    kv_offload_cpu_gb: float = 0.0
    # cap on pages moved per offload operation (one spill batch at eviction,
    # one restore chain at prefix match); 0 = unbounded, -1 (default) = AUTO:
    # the engine probes host<->device link bandwidth at startup
    # (engine/linkprobe.py) and derives the cap — 0 on PCIe-class links
    # (~10-30 GB/s, unbounded is right), a few pages on network-attached
    # chips (axon tunnel ~10-40 MB/s measured), where a 9k-token history is
    # ~300 MB and RECOMPUTING it (~9.7k tok/s chunked prefill) beats
    # restoring it ~30x — the cap bounds the engine-loop stall and the
    # prefix recomputes past it. The measured bandwidth and chosen cap are
    # exported on /metrics (vllm:kv_offload_link_bandwidth_bytes_per_sec,
    # vllm:kv_offload_max_io_pages); an explicit >= 0 value skips the probe.
    # Spill overflow beyond the cap is dropped + reported evicted (the
    # global KV index stays truthful).
    kv_offload_max_io_pages: int = -1
    # proactive-spill high watermark (fraction of the page pool): past this
    # usage the scheduler spills the coldest evictable pages to the offload
    # tier ahead of eviction, so allocation storms at >100% occupancy free
    # slots without blocking device fetches (0 or >=1 disables)
    kv_spill_watermark: float = 0.9
    kv_offload_dir: Optional[str] = None
    kv_offload_disk_gb: float = 16.0
    # warm-start manifests (kvoffload/warmstart.py, docs/failure-handling.md
    # "Restarts & rolling upgrades"): on SIGTERM drain and every
    # warm_start_interval_s the engine spills its hottest chain-head pages +
    # the prefix-index metadata to the offload tier under a generation-fenced
    # per-engine namespace; on startup it restores them BEFORE reporting
    # ready, so restarts serve warm prefixes instead of recomputing them.
    # Requires at least one offload tier (cpu/disk/remote) to persist into —
    # a DISK or REMOTE tier for state to survive process death.
    warm_start: bool = False
    # seconds between periodic manifest spills (a hard crash loses at most
    # this much warm-state delta); <= 0 spills only on drain
    warm_start_interval_s: float = 60.0
    # manifest namespace in the offload tier; engines sharing a namespace
    # fence each other by generation (rolling upgrades reuse the old pod's
    # namespace). Default: kv_instance_id, else "<model>-<port>".
    warm_start_namespace: Optional[str] = None
    # manifest size cap in pages (highest-reuse-score chain heads first)
    warm_start_max_pages: int = 256
    kv_remote_url: Optional[str] = None
    kv_serde: str = "naive"            # naive | int8 (kvoffload/serde.py)
    kv_controller_url: Optional[str] = None
    # fleet-wide KV directory (production_stack_tpu/kvdirectory,
    # docs/kv-directory.md): hosted by the cache server. When set, the engine
    # PUBLISHES directory entries (prefix-cache inserts -> resident claims;
    # confirmed proactive-spill / warm-start saves -> shared-tier claims;
    # withdraw on evict) dirty-batched every kv_directory_flush_s, and PULLS
    # fleet-warm prefixes: on request admission, chunks beyond the local
    # prefix match that the directory reports restorable are prefetched from
    # the shared tier into the local host tiers so the device-thread restore
    # finds them locally. Entries are fenced by the warm-start generation
    # (boot epoch without --warm-start), so a restarted engine's stale
    # claims expire rather than poison lookups. Usually the same address as
    # --kv-remote-url.
    kv_directory_url: Optional[str] = None
    # seconds between directory publish-batch flushes (the engine-stats
    # cadence; lower = fresher router view, more directory traffic)
    kv_directory_flush_s: float = 5.0
    # consult the directory at admission and prefetch restorable prefix
    # blobs into the local tiers (--no-kv-directory-pull = publish-only)
    kv_directory_pull: bool = True
    # cap on pages one admission may prefetch from the shared tier
    kv_directory_pull_max_pages: int = 256
    kv_instance_id: Optional[str] = None
    advertise_host: Optional[str] = None  # URL other pods reach this engine at
    # live sequence migration (production_stack_tpu/migration,
    # docs/migration.md): serve POST /migrate_out (freeze a running stream,
    # ship its KV chain through the offload tiers + its sampling/decode
    # state to a target engine), POST /migrate_in (park the continuation),
    # POST /migrate_attach (stream it), GET /migratable (controller victim
    # listing). --no-migration disables the subsystem; without an offload
    # tier migrations still work but ship zero pages (full recompute).
    migration: bool = True
    # seconds a parked /migrate_in continuation waits for its
    # /migrate_attach before it is aborted (a router that died mid-handoff
    # must not leak a decoding sequence forever)
    migrate_attach_timeout_s: float = 30.0
    # scale-up warm-up (ISSUE 10 satellite, ROADMAP item 2 remainder): pull
    # the top-N fleet-warm chunks (cache server dir_top_prefixes) into the
    # LOCAL offload tiers during engine construction — BEFORE /ready — so a
    # freshly scaled-up engine serves its first requests with warm prefix
    # hits instead of a cold cache. Needs --kv-directory-url and an offload
    # tier; 0 disables. Counted as vllm:kv_directory_prefetched_pages_total.
    warm_prefetch_on_boot: int = 0
    # disaggregated prefill role: none | producer | consumer
    kv_role: str = "none"
    kv_transfer_port: int = 55555
    kv_peer_url: Optional[str] = None
    # device-to-device KV for co-located P/D slices: pages move over the XLA
    # transfer service (jax.experimental.transfer — ICI/DCN on TPU pods)
    # instead of host serde + TCP blobs (kvoffload/transfer.py). Both roles
    # must enable it; any failure falls back to the TCP path per page.
    kv_transfer_device: bool = False
    # host other pods reach this engine's transfer server at (producer side)
    kv_transfer_device_host: str = "127.0.0.1"
    # staging budget for device-pulled pages awaiting admission (consumer)
    kv_transfer_stage_mb: int = 1024
    # peer-to-peer KV fabric (production_stack_tpu/kvfabric, docs/kv-fabric.md):
    # one engine-to-engine transfer plane for streamed disagg prefill,
    # directory resident-page pulls, and migration page-chain ships. Frames
    # are versioned + CRC'd (pages, scales) pairs, so int8 engines transfer
    # with exact scales — this is what lifts the PR 14 int8 disagg gate.
    # Every fabric path falls back to the tier path on failure (counted as
    # vllm:kv_fabric_fallbacks_total).
    kv_fabric: bool = False
    # fabric listener port; 0 binds an ephemeral port (advertised via
    # GET /kv_fabric and the directory's resident claims)
    kv_fabric_port: int = 0
    # bounded per-request retries below the per-peer breaker
    kv_fabric_retries: int = 2
    # disagg producer: the decode peer's fabric listener ("host:port") or
    # its HTTP URL (GET /kv_fabric then resolves the advertised listener —
    # needed when the peer binds an ephemeral --kv-fabric-port 0)
    kv_fabric_peer: Optional[str] = None
    # streamed disagg prefill: layers shipped per frame (the consumer
    # assembles windows into whole pages); 0 ships whole pages in one frame
    kv_fabric_stream_layers: int = 0
    # distributed tracing (production_stack_tpu/tracing, docs/tracing.md):
    # head-based sampling rate for traces ROOTED at this engine (requests
    # arriving with a traceparent header keep the router's decision); 0.0
    # turns span recording off entirely. Buffer size bounds tracer memory.
    trace_sample_rate: float = 1.0
    trace_buffer_size: int = 4096
    # engine flight recorder (tracing/flightrecorder.py,
    # docs/observability.md): a bounded ring of structured engine events —
    # scheduler dispatches, KV evict/spill/restore, admission sheds, step
    # timings, JAX compiles — exported via the debug-gated
    # GET /v1/debug/flightrecorder and auto-dumped to disk on anomalies.
    # Default ON: the hot-path cost is one dict append per dispatch
    # (bench.py asserts < 2% decode overhead as flightrecorder_overhead_ratio).
    flight_recorder: bool = True
    flight_recorder_capacity: int = 8192
    # anomaly-dump directory (engine crash / SIGTERM drain / shed burst /
    # TTFT watermark breach write a JSON window here for postmortems); None
    # falls back to $PSTPU_FLIGHTRECORDER_DIR, else disk dumps are disabled
    # (the in-memory ring and the debug endpoint still work)
    flight_recorder_dump_dir: Optional[str] = None
    # TTFT breach watermark in ms: a request finishing with TTFT above this
    # triggers a (rate-limited) anomaly dump; 0 disables
    flight_recorder_ttft_watermark_ms: float = 0.0
    # shed-burst trigger: this many admission sheds within a 5 s window
    # dump the recorder (the overload-chaos postmortem); 0 disables
    flight_recorder_shed_burst: int = 10

    @property
    def name(self) -> str:
        return self.served_model_name or self.model


# --help text for flags whose one-line meaning is not obvious from the name;
# the dataclass comments stay the authoritative long-form docs
_FLAG_HELP = {
    "interactive_reserve": (
        "waiting-queue slots reserved for interactive-class requests: batch "
        "traffic sheds (429) this many slots before the queue bound, so "
        "batch load can never starve interactive admission "
        "(docs/failure-handling.md priority classes)"
    ),
    "batch_queue_deadline_s": (
        "queue deadline for batch-class requests only (0 = inherit "
        "--queue-deadline-s); set it shorter so congestion expires batch "
        "out of the queue before any interactive request"
    ),
    "batch_prefill_share": (
        "max share of one prefill dispatch's chunk slots batch-class rows "
        "may hold while an interactive prefill is waiting (1.0 = no cap)"
    ),
    "prefill_pages_per_block": (
        "prefill kernel: KV pages landed contiguously per packed grid cell "
        "and folded as one wide matmul (0 = auto ~512 KV slots; retune with "
        "scripts/profile_prefill.py)"
    ),
    "prefill_prefetch_pages": (
        "prefill kernel: page DMAs kept in flight ahead of the cell being "
        "consumed (0 = auto ~2 cells' worth)"
    ),
    "prefill_fused_kv_write": (
        "commit each prefill chunk's K/V to its pool pages from inside the "
        "attention kernel instead of a separate post-scan scatter pass "
        "(same numerics; --no-prefill-fused-kv-write falls back)"
    ),
    "kv_cache_dtype": (
        "KV cache dtype: auto (= model dtype) | bf16 | fp16 | int8. int8 "
        "halves the decode HBM byte stream and doubles effective pool "
        "capacity (per-page scales, in-kernel dequant; serde v3 blobs ship "
        "the quantized bytes through every KV tier)"
    ),
    "warm_start": (
        "spill a warm-start manifest (hot chain-head KV pages + prefix-index "
        "metadata) to the offload tier on drain and every "
        "--warm-start-interval-s, and restore it on startup before reporting "
        "ready — engine restarts keep their hot prefixes. Needs an offload "
        "tier (--kv-offload-dir / --kv-remote-url for restart durability)"
    ),
    "warm_start_interval_s": (
        "seconds between periodic warm-start manifest spills (bounds how "
        "much warm state a hard crash loses); <= 0 spills only on SIGTERM "
        "drain"
    ),
    "warm_start_namespace": (
        "offload-tier namespace for this engine's warm-start manifests; "
        "restarts/replacements reusing a namespace fence the previous "
        "incarnation by generation (default: --kv-instance-id, else "
        "<model>-<port>)"
    ),
    "warm_start_max_pages": (
        "cap on pages a warm-start manifest covers (highest-reuse-score "
        "chain heads kept first)"
    ),
    "kv_directory_url": (
        "fleet-wide KV directory address (the cache server; usually the "
        "same as --kv-remote-url): publish this engine's prefix-cache "
        "claims and pull fleet-warm prefixes from the shared tier "
        "(docs/kv-directory.md)"
    ),
    "kv_directory_flush_s": (
        "seconds between dirty-batched directory publish flushes"
    ),
    "kv_directory_pull": (
        "prefetch directory-reported restorable prefix blobs into the "
        "local tiers at request admission (--no-kv-directory-pull = "
        "publish-only)"
    ),
    "kv_directory_pull_max_pages": (
        "cap on pages one admission may prefetch from the shared tier"
    ),
    "kv_fabric": (
        "peer-to-peer KV fabric: engine-to-engine (pages, scales) frames "
        "for streamed disagg prefill, directory resident pulls, and "
        "migration ships, with tier fallback on any failure "
        "(docs/kv-fabric.md)"
    ),
    "kv_fabric_port": (
        "fabric listener port (0 = ephemeral; advertised on GET /kv_fabric)"
    ),
    "kv_fabric_retries": (
        "bounded fabric retries per request, below the per-peer breaker"
    ),
    "kv_fabric_peer": (
        "disagg producer: decode peer's fabric listener (host:port) or its "
        "HTTP URL (resolved via GET /kv_fabric)"
    ),
    "kv_fabric_stream_layers": (
        "streamed disagg prefill: layers per fabric frame so decode starts "
        "before the last layer lands (0 = whole pages per frame)"
    ),
    "migration": (
        "serve the live-sequence-migration endpoints (/migrate_out, "
        "/migrate_in, /migrate_attach, /migratable) so running streams can "
        "move between engines without dropping (docs/migration.md); "
        "--no-migration disables"
    ),
    "migrate_attach_timeout_s": (
        "seconds a parked migrated-in continuation waits for the router's "
        "/migrate_attach before it is aborted"
    ),
    "warm_prefetch_on_boot": (
        "pull this many top fleet-warm chunks (cache server "
        "dir_top_prefixes) into the local offload tiers before /ready, so "
        "a scaled-up engine starts warm; needs --kv-directory-url (0 = off)"
    ),
    "flight_recorder": (
        "record scheduler/KV/shed/compile engine events into a bounded ring "
        "(GET /v1/debug/flightrecorder with --enable-debug-endpoints; "
        "auto-dumped on anomalies; --no-flight-recorder disables)"
    ),
    "flight_recorder_dump_dir": (
        "directory anomaly dumps (engine crash, SIGTERM drain, shed burst, "
        "TTFT watermark breach) are written to as JSON; default "
        "$PSTPU_FLIGHTRECORDER_DIR, unset = no disk dumps"
    ),
    "flight_recorder_ttft_watermark_ms": (
        "dump the flight recorder when a request's TTFT exceeds this many "
        "milliseconds (rate-limited; 0 = off)"
    ),
    "flight_recorder_shed_burst": (
        "dump the flight recorder when this many admission sheds land "
        "within 5 s (0 = off)"
    ),
}


# short/alias spellings accepted in addition to the canonical --<field-name>
# flag (parity with the reference chart's TP config, which spells the knob
# both --tensor-parallel-size and -tp)
_FLAG_ALIASES = {
    "tensor_parallel_size": ("--tensor-parallel",),
}


def add_engine_args(p: argparse.ArgumentParser) -> None:
    for f in dataclasses.fields(EngineConfig):
        flag = "--" + f.name.replace("_", "-")
        aliases = _FLAG_ALIASES.get(f.name, ())
        ftype = str(f.type)
        help_ = _FLAG_HELP.get(f.name)
        if ftype == "bool" or isinstance(f.default, bool):
            p.add_argument(flag, *aliases, action=argparse.BooleanOptionalAction,
                           default=f.default, help=help_)
        else:
            typ = str
            if "int" in ftype or isinstance(f.default, int):
                typ = int
            elif "float" in ftype or isinstance(f.default, float):
                typ = float
            p.add_argument(flag, *aliases, type=typ, default=f.default,
                           dest=f.name, help=help_)


def config_from_args(args: argparse.Namespace) -> EngineConfig:
    kwargs = {
        f.name: getattr(args, f.name)
        for f in dataclasses.fields(EngineConfig)
        if hasattr(args, f.name)
    }
    return EngineConfig(**kwargs)
